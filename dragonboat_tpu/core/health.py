"""Device-side fleet health engine: anomaly classification + top-K triage.

``core/fleet.py`` answers "what does the fleet look like" with aggregate
histograms; this module answers "which groups are sick and why".  At
10^4–10^6 lanes neither question may be answered by iterating shards on
host, so the detection runs where the state lives: one jitted pass over
the batched ``ShardState`` classifies every group into the anomaly
taxonomy below, carrying a compact fixed-width per-group ``HealthDigest``
(previous commit/applied/term/leader plus consecutive-tick counters)
between decimated health ticks, then reduces device-side to per-class
counts plus a top-K worst-offender list — so only O(K) bytes cross the
host boundary regardless of the group count.

Anomaly classes (bit ``c`` of a group's ``flags`` word):

- ``leaderless``      — occupied and leaderless for >= N consecutive
                        health ticks (persisting, not a blip)
- ``commit_stall``    — work is visibly pending (appended-but-
                        uncommitted log entries: ``last > committed``)
                        yet the commit index has been frozen for >= N
                        ticks.  Inbox occupancy is deliberately NOT the
                        pending signal — heartbeats keep inboxes
                        non-empty on a healthy idle fleet
- ``lag_divergence``  — the commit→apply lag is nonzero and has grown
                        across >= N consecutive digests
- ``churn``           — leadership handoffs (leader id changed between
                        two known leaders) arriving faster than a leaky
                        bucket drains (inc CHURN_INC, decay 1/tick)
- ``term_runaway``    — the term has risen on >= N consecutive ticks
                        (elections spinning without settling)

``fleet_health`` is jitted and tracer-safe; the digest stays device
resident (``part=G`` — the partition pass verifies no cross-G flow
outside the declared reduction below), and the ``HealthReport`` is the
single small host transfer, riding the same ``fleet_stats_every``
decimation as FleetStats.  ``recount`` is the pure-python differential
oracle the tests and the chaos detector cross-check against.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dragonboat_tpu.core import params as P

NUM_CLASSES = 5
CLASS_NAMES = ("leaderless", "commit_stall", "lag_divergence", "churn",
               "term_runaway")

#: columns of HealthReport.worst_rows (and the per-offender dict keys)
ROW_FIELDS = ("flags", "score", "term", "leader", "committed", "applied",
              "lag", "inbox", "leaderless_ticks", "stall_ticks",
              "lag_ticks", "churn_score", "runaway_ticks")
ROW_WIDTH = len(ROW_FIELDS)

DEFAULT_TOP_K = 8
#: leaky-bucket increment per observed leadership handoff (decay: 1/tick)
CHURN_INC = 4

#: severity weights per class counter — leaderless groups outrank laggy
#: ones in the triage list; within a class, longer-persisting is worse
_W_LEADERLESS, _W_STALL, _W_LAG, _W_CHURN, _W_RUNAWAY = 8, 4, 2, 2, 4


class HealthThresholds(NamedTuple):
    """Static (jit-time) anomaly trip points, in health ticks."""

    leaderless_ticks: int = 3
    stall_ticks: int = 3
    lag_ticks: int = 3
    churn_trip: int = 8      # leaky-bucket level, not ticks
    runaway_ticks: int = 4


DEFAULT_THRESHOLDS = HealthThresholds()

# Partition contract (grammar: core/kstate.py CONTRACTS; checked by
# analysis/partition.py and the contracts pass).  The digest is per-group
# device state sharded along G; the report is an aggregate over ALL
# groups — replicated, and produced by an intentional cross-G collective
# (`collective=declared` licenses the reductions/top_k/gather inside
# _fleet_health_impl that PS001 would otherwise flag).  Axis names C /
# TOPK / RW are host-side constants (NUM_CLASSES, k, ROW_WIDTH), not
# kernel geometry.
CONTRACTS = {
    "HealthDigest": {
        "prev_committed": "[G] i32 part=G",
        "prev_applied": "[G] i32 part=G",
        "prev_term": "[G] i32 part=G",
        "prev_leader": "[G] i32 part=G",
        "leaderless_ticks": "[G] i32 part=G",
        "stall_ticks": "[G] i32 part=G",
        "lag_ticks": "[G] i32 part=G",
        "churn_score": "[G] i32 part=G",
        "runaway_ticks": "[G] i32 part=G",
        "ticks": "[G] i32 part=G",
    },
    "HealthReport": {
        "class_count": "[C] i32 part=replicated collective=declared",
        "anomalous": "[] i32 part=replicated collective=declared",
        "leaderless_now": "[] i32 part=replicated collective=declared",
        "worst_idx": "[TOPK] i32 part=replicated collective=declared",
        "worst_score": "[TOPK] i32 part=replicated collective=declared",
        "worst_rows": "[TOPK,RW] i32 part=replicated collective=declared",
    },
    # one group's drill-down row (NodeHost.shard_info): every field is a
    # scalar selected out of the G-sharded state by dynamic_index — an
    # intentional cross-G fetch on the debug path, hence declared
    "ShardRow": {
        "role": "[] i32 part=replicated collective=declared",
        "term": "[] i32 part=replicated collective=declared",
        "vote": "[] i32 part=replicated collective=declared",
        "leader": "[] i32 part=replicated collective=declared",
        "committed": "[] i32 part=replicated collective=declared",
        "applied": "[] i32 part=replicated collective=declared",
        "last": "[] i32 part=replicated collective=declared",
        "stable": "[] i32 part=replicated collective=declared",
        "processed": "[] i32 part=replicated collective=declared",
        "snap_index": "[] i32 part=replicated collective=declared",
        "snap_term": "[] i32 part=replicated collective=declared",
        "inbox_occ": "[] i32 part=replicated collective=declared",
        "flags": "[] i32 part=replicated collective=declared",
        "leaderless_ticks": "[] i32 part=replicated collective=declared",
        "stall_ticks": "[] i32 part=replicated collective=declared",
        "lag_ticks": "[] i32 part=replicated collective=declared",
        "churn_score": "[] i32 part=replicated collective=declared",
        "runaway_ticks": "[] i32 part=replicated collective=declared",
    },
}


class HealthDigest(NamedTuple):
    """Fixed-width per-group carry between decimated health ticks."""

    prev_committed: jnp.ndarray   # [G]
    prev_applied: jnp.ndarray     # [G]
    prev_term: jnp.ndarray        # [G]
    prev_leader: jnp.ndarray      # [G]
    leaderless_ticks: jnp.ndarray  # [G] consecutive leaderless ticks
    stall_ticks: jnp.ndarray      # [G] consecutive frozen-commit ticks
    lag_ticks: jnp.ndarray        # [G] consecutive growing-lag ticks
    churn_score: jnp.ndarray      # [G] leaky bucket of handoffs
    runaway_ticks: jnp.ndarray    # [G] consecutive rising-term ticks
    ticks: jnp.ndarray            # [G] digest age (0 = no prior tick)


class HealthReport(NamedTuple):
    """One O(K) host transfer's worth of triage (all i32)."""

    class_count: jnp.ndarray      # [NUM_CLASSES]
    anomalous: jnp.ndarray        # [] groups with any class tripped
    leaderless_now: jnp.ndarray   # [] instantaneous leaderless count
    worst_idx: jnp.ndarray        # [K] lane indices, worst first
    worst_score: jnp.ndarray      # [K] severity (0 = healthy padding)
    worst_rows: jnp.ndarray       # [K, ROW_WIDTH] see ROW_FIELDS


def empty_digest(num_lanes: int, sharding=None) -> HealthDigest:
    """All-zero digest for ``num_lanes`` groups (ticks=0 marks every
    delta-based detector invalid until the first carry)."""
    z = jnp.zeros((num_lanes,), jnp.int32)
    d = HealthDigest(*(z for _ in HealthDigest._fields))
    if sharding is not None:
        d = jax.device_put(d, sharding)
    return d


def _fleet_health_impl(state, inbox_from, digest: HealthDigest,
                       thresholds: HealthThresholds = DEFAULT_THRESHOLDS,
                       k: int = DEFAULT_TOP_K
                       ) -> tuple[HealthReport, HealthDigest]:
    i32 = jnp.int32
    occ = (state.kind != P.K_ABSENT).any(axis=1)              # [G] bool
    valid = digest.ticks > 0                                  # [G] bool
    lag = state.committed - state.applied                     # [G] i32
    prev_lag = digest.prev_committed - digest.prev_applied
    inbox_occ = (inbox_from != 0).astype(i32).sum(axis=1)     # [G]
    pending = state.last > state.committed

    leaderless = occ & (state.leader == P.NO_LEADER)
    leaderless_ticks = jnp.where(leaderless, digest.leaderless_ticks + 1, 0)

    stalled = (occ & valid & pending
               & (state.committed == digest.prev_committed))
    stall_ticks = jnp.where(stalled, digest.stall_ticks + 1, 0)

    diverging = occ & valid & (lag > prev_lag) & (lag > 0)
    lag_ticks = jnp.where(diverging, digest.lag_ticks + 1, 0)

    # a handoff is leader A -> leader B, both known: gaining a first
    # leader (or regaining one after a leaderless window) is recovery
    handoff = (occ & valid & (state.leader != digest.prev_leader)
               & (state.leader != P.NO_LEADER)
               & (digest.prev_leader != P.NO_LEADER))
    churn_score = (jnp.maximum(digest.churn_score - 1, 0)
                   + jnp.where(handoff, CHURN_INC, 0))

    rising = occ & valid & (state.term > digest.prev_term)
    runaway_ticks = jnp.where(rising, digest.runaway_ticks + 1, 0)

    flag_mat = jnp.stack([
        (leaderless_ticks >= thresholds.leaderless_ticks).astype(i32),
        (stall_ticks >= thresholds.stall_ticks).astype(i32),
        (lag_ticks >= thresholds.lag_ticks).astype(i32),
        (churn_score >= thresholds.churn_trip).astype(i32),
        (runaway_ticks >= thresholds.runaway_ticks).astype(i32),
    ], axis=1)                                                # [G, C]
    class_count = flag_mat.sum(axis=0)                        # [C]
    bits = (1 << jnp.arange(NUM_CLASSES, dtype=i32))
    flags = (flag_mat * bits[None, :]).sum(axis=1)            # [G]
    any_flag = flags > 0
    anomalous = any_flag.astype(i32).sum()
    leaderless_now = leaderless.astype(i32).sum()

    score = (leaderless_ticks * _W_LEADERLESS + stall_ticks * _W_STALL
             + lag_ticks * _W_LAG + churn_score * _W_CHURN
             + runaway_ticks * _W_RUNAWAY)
    score = jnp.where(any_flag, score, 0)
    # lax.top_k breaks ties toward the lower index — the triage order is
    # deterministic under equal scores (tested); k is static, so small
    # engines (G < k) clamp rather than fail the trace
    k = min(int(k), score.shape[0])
    worst_score, worst_idx = jax.lax.top_k(score, k)
    rows = jnp.stack([flags, score, state.term, state.leader,
                      state.committed, state.applied, lag, inbox_occ,
                      leaderless_ticks, stall_ticks, lag_ticks,
                      churn_score, runaway_ticks], axis=1)    # [G, RW]
    worst_rows = jnp.take(rows, worst_idx, axis=0)            # [K, RW]

    report = HealthReport(
        class_count=class_count, anomalous=anomalous,
        leaderless_now=leaderless_now, worst_idx=worst_idx,
        worst_score=worst_score, worst_rows=worst_rows)
    new_digest = HealthDigest(
        prev_committed=state.committed, prev_applied=state.applied,
        prev_term=state.term, prev_leader=state.leader,
        leaderless_ticks=leaderless_ticks, stall_ticks=stall_ticks,
        lag_ticks=lag_ticks, churn_score=churn_score,
        runaway_ticks=runaway_ticks, ticks=digest.ticks + 1)
    return report, new_digest


fleet_health = jax.jit(_fleet_health_impl,
                       static_argnames=("thresholds", "k"))


class ShardRow(NamedTuple):
    """One group's introspection row: O(1) scalars, never the full
    state (see CONTRACTS)."""

    role: jnp.ndarray
    term: jnp.ndarray
    vote: jnp.ndarray
    leader: jnp.ndarray
    committed: jnp.ndarray
    applied: jnp.ndarray
    last: jnp.ndarray
    stable: jnp.ndarray
    processed: jnp.ndarray
    snap_index: jnp.ndarray
    snap_term: jnp.ndarray
    inbox_occ: jnp.ndarray
    flags: jnp.ndarray
    leaderless_ticks: jnp.ndarray
    stall_ticks: jnp.ndarray
    lag_ticks: jnp.ndarray
    churn_score: jnp.ndarray
    runaway_ticks: jnp.ndarray


def _shard_row_impl(state, inbox_from, digest: HealthDigest, lane,
                    thresholds: HealthThresholds = DEFAULT_THRESHOLDS
                    ) -> ShardRow:
    """Fetch ONE group's row by dynamic_index (``lane`` is traced — one
    compile serves every lane).  The anomaly flags reuse the digest's
    post-tick counters, so they agree with the report of the most recent
    health tick."""
    i32 = jnp.int32

    def pick(arr):
        return jax.lax.dynamic_index_in_dim(arr, lane, keepdims=False)

    counters = {f: pick(getattr(digest, f))
                for f in ("leaderless_ticks", "stall_ticks", "lag_ticks",
                          "churn_score", "runaway_ticks")}
    trips = (
        counters["leaderless_ticks"] >= thresholds.leaderless_ticks,
        counters["stall_ticks"] >= thresholds.stall_ticks,
        counters["lag_ticks"] >= thresholds.lag_ticks,
        counters["churn_score"] >= thresholds.churn_trip,
        counters["runaway_ticks"] >= thresholds.runaway_ticks,
    )
    flags = sum((t.astype(i32) << c for c, t in enumerate(trips)),
                jnp.zeros((), i32))
    return ShardRow(
        role=pick(state.role), term=pick(state.term),
        vote=pick(state.vote), leader=pick(state.leader),
        committed=pick(state.committed), applied=pick(state.applied),
        last=pick(state.last), stable=pick(state.stable),
        processed=pick(state.processed), snap_index=pick(state.snap_index),
        snap_term=pick(state.snap_term),
        inbox_occ=(pick(inbox_from) != 0).astype(i32).sum(),
        flags=flags, **counters)


shard_row = jax.jit(_shard_row_impl, static_argnames=("thresholds",))


def row_to_dict(row: ShardRow) -> dict:
    """Fetch the O(1) row to host and decode the class bitmask."""
    r = jax.device_get(row)
    d = {f: int(getattr(r, f)) for f in ShardRow._fields}
    d["classes"] = [CLASS_NAMES[c] for c in range(NUM_CLASSES)
                    if (d["flags"] >> c) & 1]
    return d


# ---------------------------------------------------------------------------
# host-side converters + exposition
# ---------------------------------------------------------------------------


def report_to_dict(report: HealthReport) -> dict:
    """Fetch to host and flatten into plain ints/dicts — the shape the
    callback gauges (and ``engine.last_health``) serve.  Healthy top-K
    padding (score 0) is dropped from ``worst``."""
    r = jax.device_get(report)
    worst = []
    for j in range(len(r.worst_idx)):
        sc = int(r.worst_score[j])
        if sc <= 0:
            continue
        row = r.worst_rows[j]
        entry = {"lane": int(r.worst_idx[j])}
        entry.update({name: int(row[i]) for i, name in enumerate(ROW_FIELDS)})
        entry["classes"] = [CLASS_NAMES[c] for c in range(NUM_CLASSES)
                            if (entry["flags"] >> c) & 1]
        worst.append(entry)
    return {
        "class_count": {CLASS_NAMES[i]: int(r.class_count[i])
                        for i in range(NUM_CLASSES)},
        "anomalous": int(r.anomalous),
        "leaderless_now": int(r.leaderless_now),
        "worst": worst,
    }


def empty_dict() -> dict:
    """All-zero health dict (merge identity for hosts with no engine)."""
    return {
        "class_count": {c: 0 for c in CLASS_NAMES},
        "anomalous": 0,
        "leaderless_now": 0,
        "worst": [],
    }


def merge_into(base: dict, other: dict, engine: str | None = None,
               k: int = DEFAULT_TOP_K) -> None:
    """Accumulate ``other`` (same shape as ``empty_dict``) into ``base``:
    counts add, worst lists merge by (score desc, lane asc) and truncate
    to ``k``.  ``engine`` tags other's offenders so a merged multi-engine
    view stays attributable."""
    base["anomalous"] += other["anomalous"]
    base["leaderless_now"] += other["leaderless_now"]
    for c in base["class_count"]:
        base["class_count"][c] += other["class_count"].get(c, 0)
    incoming = [dict(w) for w in other["worst"]]
    if engine is not None:
        for w in incoming:
            w.setdefault("engine", engine)
    merged = base["worst"] + incoming
    merged.sort(key=lambda w: (-w["score"], w["lane"]))
    base["worst"] = merged[:k]


def register_exposition(registry, source, replace: bool = False) -> None:
    """Register the health callback-gauge families on ``registry``,
    backed by ``source()`` -> health dict (or None for "no data yet").
    Idempotent when ``replace`` is False (same protocol as
    ``fleet.register_exposition``)."""
    if not replace and registry.kind_of("health_anomaly_count") is not None:
        return

    def _get() -> dict:
        d = source()
        return d if d is not None else empty_dict()

    registry.gauge_fn(
        "health_anomaly_count",
        lambda: {(c,): _get()["class_count"][c] for c in CLASS_NAMES},
        help="groups currently tripping each anomaly class",
        labelnames=("class",))
    registry.gauge_fn("health.anomalous_shards",
                      lambda: _get()["anomalous"],
                      help="groups with at least one anomaly class active")
    registry.gauge_fn("health.leaderless_now",
                      lambda: _get()["leaderless_now"],
                      help="instantaneous leaderless occupied groups")


# ---------------------------------------------------------------------------
# strict schema validation (fleet_doctor / metrics_dump --doctor)
# ---------------------------------------------------------------------------

#: breaker states transport/hub.py can report
_BREAKER_STATES = ("closed", "open", "half-open")
_RESIDENCIES = ("host", "device", "mesh")


def _req(obj: dict, key: str, typ, where: str):
    if key not in obj:
        raise ValueError(f"{where}: missing key {key!r}")
    v = obj[key]
    # bool is an int subclass; reject it where an int is required
    if typ is int and isinstance(v, bool):
        raise ValueError(f"{where}.{key}: expected int, got bool")
    if not isinstance(v, typ):
        raise ValueError(f"{where}.{key}: expected {typ}, got {type(v)}")
    return v


def _validate_offender(w: dict, where: str) -> None:
    _req(w, "lane", int, where)
    for f in ROW_FIELDS:
        _req(w, f, int, where)
    classes = _req(w, "classes", list, where)
    for c in classes:
        if c not in CLASS_NAMES:
            raise ValueError(f"{where}.classes: unknown class {c!r}")
    extra = set(w) - set(ROW_FIELDS) - {"lane", "classes", "engine"}
    if extra:
        raise ValueError(f"{where}: unexpected keys {sorted(extra)}")


def validate_health(h: dict, where: str = "health") -> None:
    """Strictly check an ``empty_dict``-shaped health snapshot (the
    ``/debug/groups`` ``health`` section and ``/healthz`` 503 body)."""
    counts = _req(h, "class_count", dict, where)
    if set(counts) != set(CLASS_NAMES):
        raise ValueError(f"{where}.class_count: classes {sorted(counts)} != "
                         f"{sorted(CLASS_NAMES)}")
    for c, n in counts.items():
        if isinstance(n, bool) or not isinstance(n, int) or n < 0:
            raise ValueError(f"{where}.class_count[{c!r}]: bad count {n!r}")
    _req(h, "anomalous", int, where)
    _req(h, "leaderless_now", int, where)
    for j, w in enumerate(_req(h, "worst", list, where)):
        _validate_offender(w, f"{where}.worst[{j}]")


def _validate_membership(mb: dict, where: str) -> None:
    for sect in ("addresses", "non_votings", "witnesses"):
        d = _req(mb, sect, dict, where)
        for r, a in d.items():
            if not str(r).lstrip("-").isdigit() or not isinstance(a, str):
                raise ValueError(f"{where}.{sect}: bad entry {r!r}: {a!r}")
    _req(mb, "config_change_id", int, where)


def validate_info(obj: dict, where: str = "/debug/groups") -> int:
    """Strictly check a ``NodeHost.info()`` payload; returns the shard
    count.  Raises ValueError naming the offending path."""
    _req(obj, "node_host_id", str, where)
    _req(obj, "raft_address", str, where)
    validate_health(_req(obj, "health", dict, where), f"{where}.health")
    shards = _req(obj, "shards", list, where)
    for i, s in enumerate(shards):
        w = f"{where}.shards[{i}]"
        if not isinstance(s, dict):
            raise ValueError(f"{w}: expected dict")
        for key in ("shard_id", "replica_id", "leader_id", "term",
                    "last_applied"):
            _req(s, key, int, w)
        _req(s, "is_leader", bool, w)
        _validate_membership(_req(s, "membership", dict, w),
                             f"{w}.membership")
        if _req(s, "resident", str, w) not in _RESIDENCIES:
            raise ValueError(f"{w}.resident: {s['resident']!r} not in "
                             f"{_RESIDENCIES}")
    return len(shards)


def validate_shard_info(obj: dict, where: str = "/debug/group") -> None:
    """Strictly check a ``NodeHost.shard_info()`` payload (one group's
    drill-down row + host registers)."""
    for key in ("shard_id", "replica_id", "leader_id", "term",
                "last_applied"):
        _req(obj, key, int, where)
    _req(obj, "is_leader", bool, where)
    _validate_membership(_req(obj, "membership", dict, where),
                         f"{where}.membership")
    if _req(obj, "resident", str, where) not in _RESIDENCIES:
        raise ValueError(f"{where}.resident: {obj['resident']!r}")
    pend = _req(obj, "pending", dict, where)
    _req(pend, "proposals", int, f"{where}.pending")
    _req(pend, "read_indexes", int, f"{where}.pending")
    ldb = _req(obj, "logdb", dict, where)
    for key in ("first_index", "last_index", "entry_count"):
        _req(ldb, key, int, f"{where}.logdb")
    snap = ldb.get("snapshot")
    if snap is not None:
        _req(snap, "index", int, f"{where}.logdb.snapshot")
        _req(snap, "term", int, f"{where}.logdb.snapshot")
    for addr, st in _req(obj, "breakers", dict, where).items():
        if st not in _BREAKER_STATES:
            raise ValueError(f"{where}.breakers[{addr!r}]: {st!r} not in "
                             f"{_BREAKER_STATES}")
    sv = _req(obj, "shard_view", dict, where)
    for key in ("shard_id", "config_change_index", "leader_id", "term"):
        _req(sv, key, int, f"{where}.shard_view")
    _req(sv, "replicas", dict, f"{where}.shard_view")
    if "device" not in obj:
        raise ValueError(f"{where}: missing key 'device'")
    dev = obj["device"]
    if dev is not None:
        for f in ShardRow._fields:
            _req(dev, f, int, f"{where}.device")
        for c in _req(dev, "classes", list, f"{where}.device"):
            if c not in CLASS_NAMES:
                raise ValueError(f"{where}.device.classes: {c!r}")


# ---------------------------------------------------------------------------
# pure-python differential oracle
# ---------------------------------------------------------------------------


def recount(state, inbox_from, digest,
            thresholds: HealthThresholds = DEFAULT_THRESHOLDS,
            k: int = DEFAULT_TOP_K) -> tuple[dict, dict]:
    """Recompute ``fleet_health`` with per-group host loops over fetched
    arrays (``jax.device_get`` the inputs first).  Returns
    ``(report_dict, digest_dict)`` where report_dict matches
    ``report_to_dict`` and digest_dict maps HealthDigest field -> list.
    This is the oracle the randomized differential and the chaos
    detector cross-check cite."""
    G = len(digest.ticks)
    out = {f: [0] * G for f in HealthDigest._fields}
    per_group = []
    counts = [0] * NUM_CLASSES
    anomalous = 0
    leaderless_now = 0
    for g in range(G):
        occ = any(int(kv) != P.K_ABSENT for kv in state.kind[g])
        valid = int(digest.ticks[g]) > 0
        committed = int(state.committed[g])
        applied = int(state.applied[g])
        term = int(state.term[g])
        leader = int(state.leader[g])
        lag = committed - applied
        prev_lag = int(digest.prev_committed[g]) - int(digest.prev_applied[g])
        inbox_occ = sum(1 for v in inbox_from[g] if int(v) != 0)
        pend = int(state.last[g]) > committed

        leaderless = occ and leader == P.NO_LEADER
        lt = int(digest.leaderless_ticks[g]) + 1 if leaderless else 0
        stalled = (occ and valid and pend
                   and committed == int(digest.prev_committed[g]))
        st = int(digest.stall_ticks[g]) + 1 if stalled else 0
        diverging = occ and valid and lag > prev_lag and lag > 0
        gt = int(digest.lag_ticks[g]) + 1 if diverging else 0
        handoff = (occ and valid and leader != int(digest.prev_leader[g])
                   and leader != P.NO_LEADER
                   and int(digest.prev_leader[g]) != P.NO_LEADER)
        cs = max(int(digest.churn_score[g]) - 1, 0) \
            + (CHURN_INC if handoff else 0)
        rising = occ and valid and term > int(digest.prev_term[g])
        rt = int(digest.runaway_ticks[g]) + 1 if rising else 0

        tripped = (lt >= thresholds.leaderless_ticks,
                   st >= thresholds.stall_ticks,
                   gt >= thresholds.lag_ticks,
                   cs >= thresholds.churn_trip,
                   rt >= thresholds.runaway_ticks)
        flags = sum(1 << c for c in range(NUM_CLASSES) if tripped[c])
        for c in range(NUM_CLASSES):
            counts[c] += int(tripped[c])
        score = (lt * _W_LEADERLESS + st * _W_STALL + gt * _W_LAG
                 + cs * _W_CHURN + rt * _W_RUNAWAY) if flags else 0
        if flags:
            anomalous += 1
        if leaderless:
            leaderless_now += 1

        row = dict(zip(ROW_FIELDS, (flags, score, term, leader, committed,
                                    applied, lag, inbox_occ, lt, st, gt,
                                    cs, rt)))
        per_group.append((score, g, row))
        new = dict(prev_committed=committed, prev_applied=applied,
                   prev_term=term, prev_leader=leader, leaderless_ticks=lt,
                   stall_ticks=st, lag_ticks=gt, churn_score=cs,
                   runaway_ticks=rt, ticks=int(digest.ticks[g]) + 1)
        for f, v in new.items():
            out[f][g] = v

    per_group.sort(key=lambda t: (-t[0], t[1]))
    worst = []
    for score, g, row in per_group[:k]:
        if score <= 0:
            continue
        entry = {"lane": g}
        entry.update(row)
        entry["classes"] = [CLASS_NAMES[c] for c in range(NUM_CLASSES)
                            if (row["flags"] >> c) & 1]
        worst.append(entry)
    report = {
        "class_count": dict(zip(CLASS_NAMES, counts)),
        "anomalous": anomalous,
        "leaderless_now": leaderless_now,
        "worst": worst,
    }
    return report, out
