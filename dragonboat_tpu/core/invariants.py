"""Runtime protocol-invariant probe: the third leg of the safety verifier.

``core/kstate.py`` declares the protocol invariants (INVARIANTS, grammar
in ``analysis/common.py``); this module evaluates them on the LIVE fleet.
One jitted pass over the batched ``ShardState`` checks every declared
invariant on every group, carrying a compact per-group
``InvariantDigest`` (the ``prev.``-referenced columns plus an age
counter) between decimated probe ticks so STEP-scoped invariants
(term/commit monotonicity, vote-at-most-once, quorum-backed commit
advance) are checked over the transition between two observations —
sound for the monotone/guarded forms kstate.py declares, at any
decimation.  The ``InvariantReport`` is the single O(1) host transfer:
a violation total, per-invariant counts, and the first-offender lane +
its violation bitmask.

A nonzero total is ALWAYS a bug — either in the kernel or in the
declared invariant — never an operational condition: the engines raise
an ``invariant_violation`` flight event and ``/healthz`` degrades to
503.  The other two legs consume the same declarations statically:
``analysis/safety.py`` (store-site abstract interpretation) and
``scripts/model_check.py`` (small-scope exhaustive exploration).

``eval_row`` / ``recount`` are the pure-python oracle the tests, the
chaos detector and the model checker cross-check against.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dragonboat_tpu.analysis.common import Invariant, parse_invariants
from dragonboat_tpu.core import params as P
from dragonboat_tpu.core.kstate import INVARIANTS as _SPECS

#: parsed invariants, declaration order (the bit order of first_mask)
PARSED: dict[str, Invariant] = parse_invariants(
    _SPECS, "core/kstate.py:INVARIANTS")
INVARIANT_NAMES = tuple(PARSED)
NUM_INVARIANTS = len(INVARIANT_NAMES)

INT32_MAX = 2**31 - 1

#: ShardState columns carried as ``prev_*`` digest fields — must cover
#: every ``prev.`` term any declared invariant references (checked below
#: at import, so adding an invariant with a new prev. field fails loudly
#: until the digest + CONTRACTS grow the column)
_PREV_FIELDS = ("term", "vote", "committed", "role", "quiesced",
                "quiesce_epoch")

_needed = {t.name
           for inv in PARSED.values()
           for c in (*inv.guards, inv.conclusion)
           for t in (c.lhs, c.rhs) if t.kind == "prev"}
if _needed - set(_PREV_FIELDS):
    raise ValueError(
        f"core/invariants.py: INVARIANTS reference prev. fields "
        f"{sorted(_needed - set(_PREV_FIELDS))} not carried by "
        "InvariantDigest — add them to _PREV_FIELDS and CONTRACTS")

# Partition contract (grammar: core/kstate.py CONTRACTS; checked by
# analysis/partition.py and the contracts pass).  The digest is per-group
# device state sharded along G; the report is an aggregate over ALL
# groups — replicated, produced by an intentional cross-G collective
# (``collective=declared`` licenses the reductions inside
# _check_invariants_impl that PS001 would otherwise flag).  Axis NI is a
# host-side constant (NUM_INVARIANTS), not kernel geometry.
CONTRACTS = {
    "InvariantDigest": {
        "prev_term": "[G] i32 part=G",
        "prev_vote": "[G] i32 part=G",
        "prev_committed": "[G] i32 part=G",
        "prev_role": "[G] i32 part=G",
        "prev_quiesced": "[G] i32 part=G",
        "prev_quiesce_epoch": "[G] i32 part=G",
        "ticks": "[G] i32 part=G",
    },
    "InvariantReport": {
        "total": "[] i32 part=replicated collective=declared",
        "checked": "[] i32 part=replicated collective=declared",
        "per_invariant": "[NI] i32 part=replicated collective=declared",
        "first_lane": "[] i32 part=replicated collective=declared",
        "first_mask": "[] i32 part=replicated collective=declared",
    },
}


class InvariantDigest(NamedTuple):
    """Fixed-width per-group carry between decimated probe ticks."""

    prev_term: jnp.ndarray       # [G]
    prev_vote: jnp.ndarray       # [G]
    prev_committed: jnp.ndarray  # [G]
    prev_role: jnp.ndarray       # [G]
    prev_quiesced: jnp.ndarray   # [G] (bool state column widened to i32)
    prev_quiesce_epoch: jnp.ndarray  # [G]
    ticks: jnp.ndarray           # [G] digest age (0 = no valid prev)


class InvariantReport(NamedTuple):
    """One O(1) host transfer's worth of verdicts (all i32)."""

    total: jnp.ndarray           # [] groups violating >= 1 invariant
    checked: jnp.ndarray         # [] occupied groups evaluated
    per_invariant: jnp.ndarray   # [NUM_INVARIANTS] violating groups
    first_lane: jnp.ndarray      # [] lowest violating lane (-1 = none)
    first_mask: jnp.ndarray      # [] that lane's violation bitmask


def empty_digest(num_lanes: int, sharding=None) -> InvariantDigest:
    """All-zero digest for ``num_lanes`` groups (ticks=0 marks every
    step-scoped invariant vacuous until the first carry)."""
    z = jnp.zeros((num_lanes,), jnp.int32)
    d = InvariantDigest(*(z for _ in InvariantDigest._fields))
    if sharding is not None:
        d = jax.device_put(d, sharding)
    return d


#: comparison semantics shared by the jitted probe (jnp arrays), the
#: pure-python oracle (ints) and the model checker
OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
}


def _quorum_arr(state, col):
    """Vectorized [G] ``quorum(col)``: the q-th largest value among
    voting members — exactly core/kernel.py _sorted_match_quorum_index
    with the leading G axis kept."""
    i32 = jnp.int32
    voting = (state.kind == P.K_VOTER) | (state.kind == P.K_WITNESS)
    mv = jnp.where(voting, col.astype(i32), INT32_MAX)
    srt = jnp.sort(mv, axis=1)       # ascending; absent lanes at the end
    nv = voting.astype(i32).sum(axis=1)
    q = nv // 2 + 1
    pos = jnp.clip(nv - q, 0, mv.shape[1] - 1)
    return jnp.take_along_axis(srt, pos[:, None], axis=1)[:, 0]


def _term_arr(t, state, inv_digest):
    if t.kind == "const":
        return jnp.int32(t.value)
    if t.kind == "param":
        return jnp.int32(int(getattr(P, t.name)))
    if t.kind == "field":
        return getattr(state, t.name).astype(jnp.int32)
    if t.kind == "prev":
        return getattr(inv_digest, "prev_" + t.name)
    if t.kind == "quorum":
        return _quorum_arr(state, getattr(state, t.name))
    raise ValueError(f"unknown invariant term kind {t.kind!r}")


def _violations(inv: Invariant, state, inv_digest, occ, valid):
    """[G] bool: rows where ``inv``'s guards all hold but the conclusion
    does not.  Step-scoped invariants are vacuous without a valid prev."""
    live = occ & valid if inv.scope == "step" else occ
    for g in inv.guards:
        live = live & OPS[g.op](_term_arr(g.lhs, state, inv_digest),
                                _term_arr(g.rhs, state, inv_digest))
    c = inv.conclusion
    holds = OPS[c.op](_term_arr(c.lhs, state, inv_digest),
                      _term_arr(c.rhs, state, inv_digest))
    return live & ~holds


def _check_invariants_impl(state, inv_digest: InvariantDigest
                           ) -> tuple[InvariantReport, InvariantDigest]:
    i32 = jnp.int32
    occ = (state.kind != P.K_ABSENT).any(axis=1)              # [G] bool
    valid = inv_digest.ticks > 0                              # [G] bool
    viol_mat = jnp.stack(
        [_violations(inv, state, inv_digest, occ, valid)
         for inv in PARSED.values()], axis=1).astype(i32)     # [G, NI]
    per_invariant = viol_mat.sum(axis=0)                      # [NI]
    bits = (1 << jnp.arange(NUM_INVARIANTS, dtype=i32))
    mask = (viol_mat * bits[None, :]).sum(axis=1)             # [G]
    bad = mask > 0
    total = bad.astype(i32).sum()
    lanes = jnp.arange(mask.shape[0], dtype=i32)
    first = jnp.min(jnp.where(bad, lanes, INT32_MAX))
    first_lane = jnp.where(total > 0, first, -1)
    first_mask = jnp.where(
        total > 0,
        jnp.take(mask, jnp.clip(first, 0, mask.shape[0] - 1)), 0)
    report = InvariantReport(
        total=total, checked=occ.astype(i32).sum(),
        per_invariant=per_invariant, first_lane=first_lane,
        first_mask=first_mask)
    new_digest = InvariantDigest(
        prev_term=state.term, prev_vote=state.vote,
        prev_committed=state.committed, prev_role=state.role,
        prev_quiesced=state.quiesced.astype(i32),
        prev_quiesce_epoch=state.quiesce_epoch,
        ticks=inv_digest.ticks + 1)
    return report, new_digest


check_invariants = jax.jit(_check_invariants_impl)


# ---------------------------------------------------------------------------
# host-side converters + exposition
# ---------------------------------------------------------------------------


def _decode_mask(mask: int) -> list[str]:
    return [INVARIANT_NAMES[i] for i in range(NUM_INVARIANTS)
            if (mask >> i) & 1]


def report_to_dict(report: InvariantReport) -> dict:
    """Fetch to host and flatten into plain ints/dicts — the shape the
    callback gauges (and ``engine.last_invariants``) serve."""
    r = jax.device_get(report)
    d = {
        "total": int(r.total),
        "checked": int(r.checked),
        "per_invariant": {INVARIANT_NAMES[i]: int(r.per_invariant[i])
                          for i in range(NUM_INVARIANTS)},
        "first": None,
    }
    if d["total"] > 0:
        d["first"] = {"lane": int(r.first_lane),
                      "invariants": _decode_mask(int(r.first_mask))}
    return d


def empty_dict() -> dict:
    """All-zero invariants dict (merge identity for hosts w/o engine)."""
    return {
        "total": 0,
        "checked": 0,
        "per_invariant": {n: 0 for n in INVARIANT_NAMES},
        "first": None,
    }


def merge_into(base: dict, other: dict, engine: str | None = None) -> None:
    """Accumulate ``other`` (same shape as ``empty_dict``) into ``base``:
    counts add; the first-offender slot keeps base's unless empty, and
    ``engine`` tags an adopted offender so a merged multi-engine view
    stays attributable."""
    base["total"] += other["total"]
    base["checked"] += other["checked"]
    for n in base["per_invariant"]:
        base["per_invariant"][n] += other["per_invariant"].get(n, 0)
    if base["first"] is None and other["first"] is not None:
        first = dict(other["first"])
        if engine is not None:
            first.setdefault("engine", engine)
        base["first"] = first


def register_exposition(registry, source, replace: bool = False) -> None:
    """Register the invariant callback-gauge families on ``registry``,
    backed by ``source()`` -> invariants dict (or None for "no data
    yet").  Idempotent when ``replace`` is False (same protocol as
    ``health.register_exposition``)."""
    if not replace \
            and registry.kind_of("invariant_violations") is not None:
        return

    def _get() -> dict:
        d = source()
        return d if d is not None else empty_dict()

    registry.gauge_fn(
        "invariant_violations",
        lambda: {(n,): _get()["per_invariant"][n]
                 for n in INVARIANT_NAMES},
        help="groups currently violating each protocol invariant",
        labelnames=("invariant",))
    registry.gauge_fn("invariants.violating_shards",
                      lambda: _get()["total"],
                      help="groups violating at least one invariant")
    registry.gauge_fn("invariants.checked_shards",
                      lambda: _get()["checked"],
                      help="occupied groups the probe evaluated")


def validate_invariants(d: dict, where: str = "invariants") -> None:
    """Strictly check an ``empty_dict``-shaped invariants snapshot (the
    ``/healthz`` 503 ``invariants`` section and chaos oracle rows)."""
    for key in ("total", "checked"):
        v = d.get(key)
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            raise ValueError(f"{where}.{key}: bad count {v!r}")
    per = d.get("per_invariant")
    if not isinstance(per, dict) or set(per) != set(INVARIANT_NAMES):
        raise ValueError(f"{where}.per_invariant: invariants "
                         f"{sorted(per) if isinstance(per, dict) else per!r}"
                         f" != {sorted(INVARIANT_NAMES)}")
    for n, v in per.items():
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            raise ValueError(f"{where}.per_invariant[{n!r}]: {v!r}")
    first = d.get("first", 0)
    if first is not None:
        if not isinstance(first, dict):
            raise ValueError(f"{where}.first: expected dict|None, "
                             f"got {first!r}")
        lane = first.get("lane")
        if isinstance(lane, bool) or not isinstance(lane, int):
            raise ValueError(f"{where}.first.lane: {lane!r}")
        for n in first.get("invariants", ()):
            if n not in INVARIANT_NAMES:
                raise ValueError(f"{where}.first: unknown invariant {n!r}")


# ---------------------------------------------------------------------------
# pure-python oracle (tests / chaos detector / model checker)
# ---------------------------------------------------------------------------


def quorum_py(match, kind) -> int:
    """Python mirror of _quorum_arr for one group's [P] rows."""
    voting = [int(k) in (P.K_VOTER, P.K_WITNESS) for k in kind]
    mv = sorted(int(m) if v else INT32_MAX for m, v in zip(match, voting))
    nv = sum(voting)
    pos = min(max(nv - (nv // 2 + 1), 0), len(mv) - 1)
    return mv[pos]


def _term_row(t, cur: dict, prev: dict | None):
    if t.kind == "const":
        return t.value
    if t.kind == "param":
        return int(getattr(P, t.name))
    if t.kind == "field":
        return int(cur[t.name])
    if t.kind == "prev":
        return int(prev[t.name])
    if t.kind == "quorum":
        return quorum_py(cur[t.name], cur["kind"])
    raise ValueError(f"unknown invariant term kind {t.kind!r}")


def eval_row(inv: Invariant, cur: dict, prev: dict | None) -> bool:
    """True iff ``inv`` is VIOLATED on one group's row.  ``cur`` maps
    ShardState field -> int ([G] columns) or [P] sequence (``match`` /
    ``kind``); ``prev`` maps prev-field -> int, or None for "no prior
    observation" (step-scoped invariants pass vacuously)."""
    if inv.scope == "step" and prev is None:
        return False
    for g in inv.guards:
        if not OPS[g.op](_term_row(g.lhs, cur, prev),
                         _term_row(g.rhs, cur, prev)):
            return False
    c = inv.conclusion
    return not OPS[c.op](_term_row(c.lhs, cur, prev),
                         _term_row(c.rhs, cur, prev))


def recount(state, inv_digest) -> tuple[dict, dict]:
    """Recompute ``check_invariants`` with per-group host loops over
    fetched arrays (``jax.device_get`` the inputs first).  Returns
    ``(report_dict, digest_dict)`` where report_dict matches
    ``report_to_dict`` and digest_dict maps InvariantDigest field ->
    list — the oracle the probe's differential tests cite."""
    G = len(inv_digest.ticks)
    counts = {n: 0 for n in INVARIANT_NAMES}
    total = checked = 0
    first = None
    out = {f: [0] * G for f in InvariantDigest._fields}
    for g in range(G):
        occ = any(int(k) != P.K_ABSENT for k in state.kind[g])
        valid = int(inv_digest.ticks[g]) > 0
        cur = {"kind": [int(v) for v in state.kind[g]]}
        for f in sorted({f for inv in PARSED.values() for f in inv.fields}):
            col = getattr(state, f)[g]
            cur[f] = ([int(v) for v in col] if getattr(col, "ndim", 0)
                      else int(col))
        prev = ({f: int(getattr(inv_digest, "prev_" + f)[g])
                 for f in _PREV_FIELDS} if valid else None)
        if occ:
            checked += 1
        mask = 0
        for i, inv in enumerate(PARSED.values()):
            if occ and eval_row(inv, cur, prev):
                counts[inv.name] += 1
                mask |= 1 << i
        if mask:
            total += 1
            if first is None:
                first = {"lane": g, "invariants": _decode_mask(mask)}
        new = {"prev_" + f: int(getattr(state, f)[g])
               for f in _PREV_FIELDS}
        new["ticks"] = int(inv_digest.ticks[g]) + 1
        for f, v in new.items():
            out[f][g] = v
    report = {"total": total, "checked": checked,
              "per_invariant": counts, "first": first}
    return report, out
