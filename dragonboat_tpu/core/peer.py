"""Peer — the message-in/Update-out API over the raft core.

Parity with the reference's ``internal/raft/peer.go``: every input to the
protocol is modelled as a message; the output is a :class:`raftpb.Update`
batch that the engine persists/sends/applies and then ``commit()``s back.
The batched device kernel produces the same Update contract per shard, so
the engine above is executor-agnostic.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Sequence

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core.logentry import ILogDBReader
from dragonboat_tpu.core.pycore import CoreConfig, Raft

# apply-batch pagination (reference settings.Soft MaxEntriesToApplySize)
MAX_APPLY_SIZE = 8 * 1024 * 1024


class Peer:
    """Single-shard protocol driver — parity internal/raft/peer.go:56-208."""

    def __init__(self, raft: Raft) -> None:
        self.raft = raft
        self.prev_state = self._raft_state()

    # -- construction ---------------------------------------------------

    @staticmethod
    def launch(
        cfg: CoreConfig,
        logdb: ILogDBReader,
        addresses: dict[int, str],
        initial: bool,
        new_node: bool,
        rng=None,
    ) -> "Peer":
        """Start or restart a raft node — parity peer.go:64 (Launch).

        When ``initial and new_node``, bootstrap config-change entries for the
        initial membership are appended at term 1 and marked committed
        (peer.go:404 bootstrap)."""
        r = Raft(cfg, logdb, rng=rng)
        # persisted-state restore is the caller's job via raft.load_state
        p = Peer(r)
        if initial and new_node:
            r.become_follower(1, 0)
            ents = []
            for i, rid in enumerate(sorted(addresses)):
                cc = pb.ConfigChange(
                    type=pb.ConfigChangeType.ADD_NODE,
                    replica_id=rid,
                    address=addresses[rid],
                    initialize=True,
                )
                ents.append(
                    pb.Entry(
                        type=pb.EntryType.CONFIG_CHANGE,
                        term=1,
                        index=i + 1,
                        cmd=pb.encode_config_change(cc),
                    )
                )
            r.log.append(ents)
            r.log.committed = len(ents)
            for rid in sorted(addresses):
                r.add_node(rid)
        return p

    def _raft_state(self) -> pb.State:
        return pb.State(
            term=self.raft.term, vote=self.raft.vote, commit=self.raft.log.committed
        )

    # -- input translators (peer.go:81-170) -----------------------------

    def tick(self) -> None:
        self.raft.handle(pb.Message(type=pb.MessageType.LOCAL_TICK, reject=False))

    def quiesced_tick(self) -> None:
        self.raft.handle(pb.Message(type=pb.MessageType.LOCAL_TICK, reject=True))

    def query_raft_log(self, first: int, last: int, max_size: int) -> None:
        self.raft.handle(
            pb.Message(
                type=pb.MessageType.LOG_QUERY, from_=first, to=last, hint=max_size
            )
        )

    def request_leader_transfer(self, target: int) -> None:
        self.raft.handle(
            pb.Message(
                type=pb.MessageType.LEADER_TRANSFER,
                to=self.raft.replica_id,
                hint=target,
            )
        )

    def propose_entries(self, ents: Sequence[pb.Entry]) -> None:
        self.raft.handle(
            pb.Message(
                type=pb.MessageType.PROPOSE,
                from_=self.raft.replica_id,
                entries=tuple(ents),
            )
        )

    def propose_config_change(self, cc: pb.ConfigChange, key: int) -> None:
        self.raft.handle(
            pb.Message(
                type=pb.MessageType.PROPOSE,
                entries=(
                    pb.Entry(
                        type=pb.EntryType.CONFIG_CHANGE,
                        cmd=pb.encode_config_change(cc),
                        key=key,
                    ),
                ),
            )
        )

    def apply_config_change(self, cc: pb.ConfigChange) -> None:
        if cc.replica_id == 0:
            self.raft.pending_config_change = False
            return
        self.raft.handle(
            pb.Message(
                type=pb.MessageType.CONFIG_CHANGE_EVENT,
                reject=False,
                hint=cc.replica_id,
                hint_high=int(cc.type),
            )
        )

    def reject_config_change(self) -> None:
        self.raft.handle(
            pb.Message(type=pb.MessageType.CONFIG_CHANGE_EVENT, reject=True)
        )

    def restore_remotes(self, ss: pb.Snapshot) -> None:
        self.raft.handle(
            pb.Message(type=pb.MessageType.SNAPSHOT_RECEIVED, snapshot=ss)
        )

    def report_unreachable_node(self, replica_id: int) -> None:
        self.raft.handle(
            pb.Message(type=pb.MessageType.UNREACHABLE, from_=replica_id)
        )

    def report_snapshot_status(self, replica_id: int, reject: bool) -> None:
        self.raft.handle(
            pb.Message(
                type=pb.MessageType.SNAPSHOT_STATUS, from_=replica_id, reject=reject
            )
        )

    def read_index(self, ctx: pb.SystemCtx) -> None:
        self.raft.handle(
            pb.Message(
                type=pb.MessageType.READ_INDEX, hint=ctx.low, hint_high=ctx.high
            )
        )

    def notify_raft_last_applied(self, last_applied: int) -> None:
        self.raft.applied = last_applied

    def handle(self, m: pb.Message) -> None:
        """External message entry — drops responses from unknown peers
        (peer.go:183-194)."""
        if m.is_local():
            raise AssertionError("local message sent to handle()")
        known = self.raft.get_remote(m.from_) is not None
        if known or not m.is_response():
            self.raft.handle(m)

    # -- Update assembly (peer.go:198-292, 432) --------------------------

    def has_update(self, more_to_apply: bool) -> bool:
        r = self.raft
        return bool(
            r.log.entries_to_save()
            or r.log_query_result is not None
            or r.leader_update is not None
            or r.msgs
            or (more_to_apply and r.log.has_entries_to_apply())
            or self._raft_state() != self.prev_state
            or (r.log.inmem.snapshot is not None and not r.log.inmem.snapshot.is_empty())
            or r.ready_to_read
            or r.dropped_entries
            or r.dropped_read_indexes
        )

    def has_entry_to_apply(self) -> bool:
        return self.raft.log.has_entries_to_apply()

    def get_update(self, more_to_apply: bool, last_applied: int) -> pb.Update:
        r = self.raft
        committed: tuple[pb.Entry, ...] = ()
        more = False
        if more_to_apply:
            committed = tuple(r.log.entries_to_apply(MAX_APPLY_SIZE))
            if committed:
                more = committed[-1].index < r.log.committed
        state = pb.State()
        cur = self._raft_state()
        if cur != self.prev_state:
            state = cur
        snapshot = pb.Snapshot()
        if r.log.inmem.snapshot is not None:
            snapshot = r.log.inmem.snapshot
        ud = pb.Update(
            shard_id=r.shard_id,
            replica_id=r.replica_id,
            state=state,
            entries_to_save=tuple(r.log.entries_to_save()),
            committed_entries=committed,
            more_committed_entries=more,
            snapshot=snapshot,
            ready_to_reads=tuple(r.ready_to_read),
            messages=tuple(replace(m, shard_id=r.shard_id) for m in r.msgs),
            last_applied=last_applied,
            dropped_entries=tuple(r.dropped_entries),
            dropped_read_indexes=tuple(r.dropped_read_indexes),
            log_query_result=r.log_query_result or pb.LogQueryResult(),
            leader_update=r.leader_update,
        )
        self._validate_update(ud)
        ud = replace(ud, fast_apply=self._fast_apply(ud))
        ud = replace(ud, update_commit=self._get_update_commit(ud))
        return ud

    @staticmethod
    def _fast_apply(ud: pb.Update) -> bool:
        """Committed entries can be applied without waiting for fsync iff
        none of them are in this Update's to-save batch (peer.go:210-226)."""
        if not ud.snapshot.is_empty():
            return False
        if ud.committed_entries and ud.entries_to_save:
            last_apply = ud.committed_entries[-1].index
            first_save = ud.entries_to_save[0].index
            last_save = ud.entries_to_save[-1].index
            if first_save <= last_apply <= last_save:
                return False
        return True

    @staticmethod
    def _validate_update(ud: pb.Update) -> None:
        if ud.state.commit > 0 and ud.committed_entries:
            if ud.committed_entries[-1].index > ud.state.commit:
                raise AssertionError("applying uncommitted entry")
        if ud.committed_entries and ud.entries_to_save:
            if ud.committed_entries[-1].index > ud.entries_to_save[-1].index:
                raise AssertionError("applying unsaved entry")

    @staticmethod
    def _get_update_commit(ud: pb.Update) -> pb.UpdateCommit:
        uc = pb.UpdateCommit(
            ready_to_read=len(ud.ready_to_reads),
            last_applied=ud.last_applied,
        )
        processed = uc.processed
        if ud.committed_entries:
            processed = ud.committed_entries[-1].index
        stable_log_to, stable_log_term = 0, 0
        if ud.entries_to_save:
            stable_log_to = ud.entries_to_save[-1].index
            stable_log_term = ud.entries_to_save[-1].term
        stable_snapshot_to = 0
        if not ud.snapshot.is_empty():
            stable_snapshot_to = ud.snapshot.index
            processed = max(processed, stable_snapshot_to)
        return pb.UpdateCommit(
            processed=processed,
            last_applied=ud.last_applied,
            stable_log_to=stable_log_to,
            stable_log_term=stable_log_term,
            stable_snapshot_to=stable_snapshot_to,
            ready_to_read=len(ud.ready_to_reads),
        )

    def commit(self, ud: pb.Update) -> None:
        """Mark an Update as processed — parity peer.go:292 (Commit)."""
        r = self.raft
        r.msgs = []
        r.log_query_result = None
        r.leader_update = None
        r.dropped_entries = []
        r.dropped_read_indexes = []
        if not ud.state.is_empty():
            self.prev_state = ud.state
        if ud.update_commit.ready_to_read > 0:
            r.ready_to_read = r.ready_to_read[ud.update_commit.ready_to_read :]
        r.log.commit_update(ud.update_commit)

    def notify_config_change_applied(self) -> None:
        pass

    # convenience accessors used by node/tests
    @property
    def leader_id(self) -> int:
        return self.raft.leader_id

    def is_leader(self) -> bool:
        return self.raft.is_leader()
