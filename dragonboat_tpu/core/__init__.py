"""dragonboat_tpu.core — the Raft protocol core.

Two interchangeable executors implement the same message-in/Update-out
contract (the reference models all raft inputs as messages,
``internal/raft/peer.go:30-37``):

- :mod:`.pycore` — full-fidelity single-shard core in plain Python.  Runs the
  etcd-derived conformance suites and serves as the host slow path for
  variable-width operations (snapshot install, membership restore).
- :mod:`.kernel` — the batched SoA JAX kernel advancing ``[G]`` shards in
  lockstep per step; differentially tested against :mod:`.pycore`.
"""
