"""Proposal-lifecycle tracer: end-to-end spans over the commit path.

PR 4's fleet telemetry answers "what is the p50"; this module answers
"where does it go".  A deterministic 1-in-N sample of proposal keys
(``ExpertConfig.trace_sample_every``; entry keys are process-unique,
``request.PendingProposal._seq``) gets a span attached at ``propose``;
every later hop of the host plumbing stamps a monotonic timestamp onto
it — staging build, dispatch, pipelined retirement, logdb save/fsync,
apply, in-proc transport send/recv — and the future's ack completes it.

Completed traces feed three sinks:

- per-stage latency attribution: ``commit_stage_us{stage=...}``
  histograms in the shared telemetry registry (each stage's value is
  the delta from the previous stamp — the stage's own dwell time);
- a bounded ring of full traces, exported as Chrome-trace-event JSON
  (Perfetto / ``chrome://tracing`` loadable) from ``/trace`` on the
  metrics endpoint.  Span names match the ``tracing.annotate`` device
  annotations (``ANNOTATION_OF``) so a host trace loads side by side
  with a ``jax.profiler`` capture of the same run;
- slow-commit flight-recorder events: a sampled commit slower than the
  configured SLO records a ``flight.SLOW_COMMIT`` with its full stage
  breakdown.

Discipline: this module is in BOTH the concurrency and determinism
lint scopes.  It never names a wall clock — the microsecond clock is
injected (``tracing.monotonic_us`` by default, a counter in tests), the
same instruments-observe-caller-values doctrine as telemetry.py — and
all mutable state is ``guarded-by: mu``.  Spans that can no longer
complete (dropped/timed-out/terminated futures, in-flight node
removals on the pipelined path) are SCRUBBED, not leaked: every
completion verb of the proposal book ends its span.
"""

from __future__ import annotations

import threading
from collections import deque

from dragonboat_tpu import flight
from dragonboat_tpu import telemetry
from dragonboat_tpu.tracing import monotonic_us

# -- stage taxonomy (canonical order along the commit path) -----------------

STAGE_PROPOSE = "propose"          # client enqueue (request book)
STAGE_STAGE = "stage"              # host staging build (_stage_props)
STAGE_DISPATCH = "dispatch"        # jitted step / step_donated issued
STAGE_RETIRE = "retire"            # output pass entered (_process_outputs;
#                                    one step late on the pipelined path)
STAGE_SAVE = "save"                # pb.Update batch assembled
STAGE_FSYNC = "fsync"              # durable logdb flush completed
STAGE_APPLY_QUEUE = "apply_queue"  # handed to the apply pool
STAGE_APPLY = "apply"              # RSM update executed
STAGE_HUB_SEND = "hub_send"        # replicate left the transport hub
STAGE_HUB_RECV = "hub_recv"        # replicate arrived (every transport)
STAGE_ACK_RETURN = "ack_return"    # quorum ack returned to the origin
#                                    host (stamped by fabric.METER off
#                                    the trace header's return context)
STAGE_ACK = "ack"                  # future completed

STAGES = (STAGE_PROPOSE, STAGE_STAGE, STAGE_DISPATCH, STAGE_RETIRE,
          STAGE_SAVE, STAGE_FSYNC, STAGE_APPLY_QUEUE, STAGE_APPLY,
          STAGE_HUB_SEND, STAGE_HUB_RECV, STAGE_ACK_RETURN, STAGE_ACK)

# read-path stage taxonomy (ROADMAP item 3's attribution prerequisite):
# a sampled ReadIndex gets its own span kind with these stamps
STAGE_READ_PROPOSE = "read_propose"  # ReadIndex enqueued (request book)
STAGE_READ_QUORUM = "read_quorum"    # quorum round confirmed the index
STAGE_READ_SERVE = "read_serve"      # applied index caught up, read served

READ_STAGES = (STAGE_READ_PROPOSE, STAGE_READ_QUORUM, STAGE_READ_SERVE)

KIND_PROPOSAL = "proposal"
KIND_READ = "read"

# host stage -> the tracing.annotate span name covering the same work in
# a jax.profiler device capture; Perfetto shows both timelines and these
# names line the two up
ANNOTATION_OF = {
    STAGE_DISPATCH: "kernel_engine.step",
    STAGE_RETIRE: "kernel_engine.process_outputs",
}

DEFAULT_SAMPLE_EVERY = 64


class _Span:
    """One sampled span's stamp list (append-only, time-ordered)."""

    __slots__ = ("key", "shard_id", "kind", "stamps")

    def __init__(self, key: int, shard_id: int,
                 kind: str = KIND_PROPOSAL) -> None:
        self.key = key
        self.shard_id = shard_id
        self.kind = kind
        self.stamps: list[tuple[str, int]] = []   # (stage, t_us)


class LifecycleTracer:
    """Process-wide span book + completed-trace ring + sinks."""

    def __init__(self, sample_every: int = 0, clock=None,
                 ring_size: int = 256, max_active: int = 4096,
                 slow_commit_us: int = 0, registry=None,
                 recorder=None) -> None:
        if ring_size <= 0:
            raise ValueError(f"ring_size must be positive, got {ring_size}")
        self.mu = threading.Lock()
        self._clock = clock if clock is not None else monotonic_us
        self._every = max(0, int(sample_every))
        self._slow_us = max(0, int(slow_commit_us))
        self._max_active = max(1, int(max_active))
        self._spans: dict[int, _Span] = {}          # guarded-by: mu
        self._ring: deque = deque(maxlen=ring_size)  # guarded-by: mu
        self._dropped = 0        # spans refused at the active cap
        self._scrubbed = 0       # spans ended without an ack
        self._finished = 0       # spans completed through finish()
        self._registry = registry if registry is not None \
            else telemetry.GLOBAL
        self._recorder = recorder if recorder is not None \
            else flight.RECORDER
        # completion hooks (fabric.py's hop census): fired OUTSIDE mu
        # with (key, kind) after a span finishes / is scrubbed
        self._on_finish = None
        self._on_scrub = None
        self._stage_hist = self._registry.histogram(
            "commit_stage_us",
            help="per-stage commit latency attribution of sampled "
                 "proposals (stage=total is propose->ack)",
            labelnames=("stage",))

    # -- configuration / cheap hot-path guards ----------------------------

    @property
    def enabled(self) -> bool:
        return self._every > 0

    def sampled(self, key: int) -> bool:
        """Deterministic 1-in-N selection over process-unique keys."""
        every = self._every
        return every > 0 and key % every == 0

    def configure(self, sample_every: int | None = None,
                  slow_commit_us: int | None = None) -> None:
        """Re-point the process-global tracer at a host's expert config
        (NodeHost.__init__); None leaves a knob unchanged."""
        with self.mu:
            if sample_every is not None:
                self._every = max(0, int(sample_every))
            if slow_commit_us is not None:
                self._slow_us = max(0, int(slow_commit_us))

    def set_hooks(self, on_finish=None, on_scrub=None) -> None:
        """Register span-completion callbacks ``fn(key, kind)``, fired
        outside ``mu`` after ``finish``/``scrub`` retire a live span.
        One consumer (``fabric.METER``'s hop census); later writers
        replace earlier ones.  Callbacks must not call back into the
        tracer's span verbs for the same key."""
        with self.mu:
            self._on_finish = on_finish
            self._on_scrub = on_scrub

    # -- span lifecycle ----------------------------------------------------

    def begin(self, key: int, shard_id: int = 0) -> bool:
        """Open a span for a sampled key (no-op otherwise).  Bounded: at
        ``max_active`` live spans new ones are counted and refused — a
        leak upstream must degrade the sample, never host memory."""
        if not self.sampled(key):
            return False
        t = self._clock()
        sp = _Span(key, shard_id)
        sp.stamps.append((STAGE_PROPOSE, t))
        with self.mu:
            if key in self._spans:
                return False
            if len(self._spans) >= self._max_active:
                self._dropped += 1
                return False
            self._spans[key] = sp
        return True

    def begin_read(self, key: int, shard_id: int = 0) -> bool:
        """Open a READ span for a sampled ReadIndex key: same book and
        bounds as ``begin``, first stamp ``read_propose``, completed by
        ``finish`` at serve time with a ``read_total`` observation."""
        if not self.sampled(key):
            return False
        t = self._clock()
        sp = _Span(key, shard_id, kind=KIND_READ)
        sp.stamps.append((STAGE_READ_PROPOSE, t))
        with self.mu:
            if key in self._spans:
                return False
            if len(self._spans) >= self._max_active:
                self._dropped += 1
                return False
            self._spans[key] = sp
        return True

    def stamp(self, key: int, stage: str) -> None:
        """Record one stage stamp on a live sampled span (cheap no-op
        for unsampled keys and completed/scrubbed spans)."""
        if not self.sampled(key):
            return
        t = self._clock()
        with self.mu:
            sp = self._spans.get(key)
            if sp is not None:
                sp.stamps.append((stage, t))

    def finish(self, key: int) -> None:
        """Complete a span at future-ack time: stamp the closing stage
        (``ack`` for proposals, ``read_serve`` for reads), feed the
        per-stage histograms, retire the trace into the ring, and record
        a slow-commit flight event when the SLO is exceeded."""
        if not self.sampled(key):
            return
        t = self._clock()
        with self.mu:
            sp = self._spans.pop(key, None)
            if sp is None:
                return
            closing = STAGE_ACK if sp.kind == KIND_PROPOSAL \
                else STAGE_READ_SERVE
            sp.stamps.append((closing, t))
            self._finished += 1
            total = sp.stamps[-1][1] - sp.stamps[0][1]
            trace = {"key": sp.key, "shard_id": sp.shard_id,
                     "kind": sp.kind, "stamps": list(sp.stamps),
                     "total_us": total}
            self._ring.append(trace)
            slow = (sp.kind == KIND_PROPOSAL and self._slow_us > 0
                    and total >= self._slow_us)
            hook = self._on_finish
        # sinks run outside mu: the histogram and recorder take their
        # own locks, and nothing here needs the span book anymore
        prev = sp.stamps[0][1]
        for stage, ts in sp.stamps[1:]:
            self._stage_hist.labels(stage).observe(ts - prev)
            prev = ts
        self._stage_hist.labels(
            "total" if sp.kind == KIND_PROPOSAL else "read_total"
        ).observe(total)
        if slow:
            t0 = sp.stamps[0][1]
            self._recorder.record(
                flight.SLOW_COMMIT, key=sp.key, shard_id=sp.shard_id,
                total_us=total, slo_us=self._slow_us,
                stages=[[stage, ts - t0] for stage, ts in sp.stamps])
        if hook is not None:
            hook(key, sp.kind)

    def scrub(self, key: int) -> None:
        """End a span that can no longer complete (dropped / timed-out /
        terminated future, in-flight node removal) — the span is
        discarded, never retired as a trace and never fed to the sinks."""
        if not self.sampled(key):
            return
        with self.mu:
            sp = self._spans.pop(key, None)
            if sp is not None:
                self._scrubbed += 1
            hook = self._on_scrub
        if sp is not None and hook is not None:
            hook(key, sp.kind)

    # -- introspection / export -------------------------------------------

    def active_count(self) -> int:
        with self.mu:
            return len(self._spans)

    def counts(self) -> dict:
        with self.mu:
            return {"active": len(self._spans), "finished": self._finished,
                    "scrubbed": self._scrubbed, "dropped": self._dropped}

    def completed(self) -> list[dict]:
        """Retained completed traces, oldest first (fresh copies)."""
        with self.mu:
            return [dict(tr, stamps=list(tr["stamps"]))
                    for tr in self._ring]

    def reset(self) -> None:
        """Drop spans, traces and counters (test isolation)."""
        with self.mu:
            self._spans.clear()
            self._ring.clear()
            self._dropped = 0
            self._scrubbed = 0
            self._finished = 0

    def export_chrome_trace(self) -> dict:
        """The completed-trace ring as a Chrome-trace-event JSON object
        (the ``traceEvents`` array form Perfetto and chrome://tracing
        load directly).  One complete ``"ph": "X"`` event per stage,
        ``dur`` = dwell until the next stamp; ``pid`` groups by shard,
        ``tid`` is the proposal key, so each proposal renders as one
        row of contiguous stage blocks.  ``args.annotation`` carries the
        matching ``tracing.annotate`` span name for stitching against a
        ``jax.profiler`` capture of the same run."""
        events = []
        for tr in self.completed():
            stamps = tr["stamps"]
            for i, (stage, ts) in enumerate(stamps):
                dur = (stamps[i + 1][1] - ts) if i + 1 < len(stamps) else 0
                events.append({
                    "name": stage, "cat": tr.get("kind", KIND_PROPOSAL),
                    "ph": "X", "ts": ts, "dur": dur,
                    "pid": tr["shard_id"], "tid": tr["key"],
                    "args": {"key": tr["key"],
                             "annotation": ANNOTATION_OF.get(stage, "")},
                })
        return {"traceEvents": events}


def validate_chrome_trace(obj) -> int:
    """Strict validation of a Chrome-trace-event JSON object; returns
    the event count.  Raises ``ValueError`` on: a non-``traceEvents``
    shape, a missing required key (``name``/``ph``/``ts``/``pid``/
    ``tid``), a negative timestamp or duration, or timestamps that go
    BACKWARDS within one (pid, tid) span — the stamps of a span are
    appended in clock order, so a regression means a corrupt export.
    Shared by the exporter's tests and ``scripts/metrics_dump.py
    --trace`` (the same parser-strictness doctrine as
    ``telemetry.parse_exposition``)."""
    if isinstance(obj, dict):
        events = obj.get("traceEvents")
    elif isinstance(obj, list):   # Chrome also accepts the bare array
        events = obj
    else:
        raise ValueError(f"trace must be an object or array, "
                         f"got {type(obj).__name__}")
    if not isinstance(events, list):
        raise ValueError("traceEvents must be an array")
    last_ts: dict[tuple, int] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i}: not an object")
        for req in ("name", "ph", "ts", "pid", "tid"):
            if req not in ev:
                raise ValueError(f"event {i}: missing required key {req!r}")
        ts = ev["ts"]
        if not isinstance(ts, (int, float)) or ts < 0:
            raise ValueError(f"event {i}: ts must be a non-negative "
                             f"number, got {ts!r}")
        dur = ev.get("dur", 0)
        if not isinstance(dur, (int, float)) or dur < 0:
            raise ValueError(f"event {i}: dur must be a non-negative "
                             f"number, got {dur!r}")
        span = (ev["pid"], ev["tid"])
        prev = last_ts.get(span)
        if prev is not None and ts < prev:
            raise ValueError(
                f"event {i}: ts {ts} goes backwards within span "
                f"pid={ev['pid']} tid={ev['tid']} (prev {prev})")
        last_ts[span] = ts
    return len(events)


# process-wide tracer: the request books, engines, logdb and transport
# stamp here so one ring shows complete spans across every host in the
# process (the same one-recorder doctrine as flight.RECORDER).  Default
# sampling is 1/64; a NodeHost re-points it at its expert config.
TRACER = LifecycleTracer(sample_every=DEFAULT_SAMPLE_EVERY)
