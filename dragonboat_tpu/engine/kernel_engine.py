"""KernelEngine — device-resident shards behind the real client API.

The reference advances each shard with per-shard goroutine work queues
(engine.go:1107-1364: step workers → one batched fsync → send → apply).
Here every device-resident shard is one lane of a batched ``[G]`` kernel
state (core/kernel.py) and ONE jitted vmapped step advances all of them;
the host's job per step is pure marshaling:

  1. drain client/transport queues into ``StepInput`` lanes + ``Inbox``
     slots (payloads stay in a host-side mirror — the device ring holds
     terms only, kstate.py:59);
  2. run the jitted step;
  3. assemble one ``pb.Update`` batch and call ``save_raft_state`` once
     (THE fsync — raftio/logdb.go:78-83), sending Replicates before it
     (thesis §10.2.1, engine.go:1332-1343) and everything else after;
  4. release committed entries to the RSMs, complete request futures,
     and fire events.

Shards escalate out of the kernel (``needs_host``: a peer needs an
InstallSnapshot stream, the ring overflowed, a restore arrived) by
EVICTION: all state is already durable through the shared LogDB, so the
host builds a regular pycore ``Node`` from the persisted state and the
shard continues on the loopback engine.  That is the slow path the
VERDICT's round-1 review found missing — produced but never consumed.

ReadIndex across hosts: a follower-host read forwards a READ_INDEX
message to the leader host (raft.go:1296 leader-forwarding), the leader
feeds it to its kernel lane as a batched-read ctx and answers with
READ_INDEX_RESP — the kernel itself only ever sees leader-local reads.

Pipelining (``pipeline_depth``): at depth 0 each ``step_all`` runs the
serial loop — stage, dispatch, fetch, process — and is the differential
oracle.  At depth 1 the loop is software-pipelined: staging for step N
builds into the ALTERNATE half of a double-buffered inbox/input pair
while the device still executes step N-1; step N-1's outputs are then
retired (the async fetch is consumed one step late) BEFORE step N is
dispatched through the donating jit entry (core/kernel.py
``step_donated``) — the retire-before-dispatch order is the donation
contract: dispatch hands the state/inbox/input buffers to XLA, so
every read of the previous state (lt rows for the update batch, the
wit-snap compaction floor) must complete first, and the host never
touches a buffer again after its dispatch.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace as _dc_replace

import jax.numpy as jnp
import numpy as np

from dragonboat_tpu import capacity as _capacity
from dragonboat_tpu import lifecycle
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.tracing import annotate, stop_env_trace
from dragonboat_tpu.config import Config
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kernel import (
    FLAG_CLASSES,
    output_row_flags,
    step as kernel_step,
    step_donated as kernel_step_donated,
)
from dragonboat_tpu.core import router as _router
from dragonboat_tpu.core.kstate import (
    Inbox,
    ShardState,
    StepInput,
    init_state,
)
from dragonboat_tpu.events import EventHub
from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.node import Node, _SnapshotRequest
from dragonboat_tpu.raftio import LeaderInfo
from dragonboat_tpu.request import RequestResultCode
from dragonboat_tpu.statemachine import Result

_LOG = get_logger("engine")

MT = pb.MessageType

# message types a kernel lane consumes directly (core/kernel.py
# _process_family dispatch set)
_KERNEL_MTYPES = frozenset({
    MT.REPLICATE, MT.REPLICATE_RESP, MT.HEARTBEAT, MT.HEARTBEAT_RESP,
    MT.REQUEST_VOTE, MT.REQUEST_VOTE_RESP, MT.REQUEST_PREVOTE,
    MT.REQUEST_PREVOTE_RESP, MT.TIMEOUT_NOW, MT.UNREACHABLE,
    MT.SNAPSHOT_STATUS,
})

# column per message class in the [G, C] output_row_flags matrix
# (core/kernel.py FLAG_CLASSES order)
_F = {c: i for i, c in enumerate(FLAG_CLASSES)}
_F_RESP, _F_REP, _F_HB, _F_VOTE = _F["resp"], _F["rep"], _F["hb"], _F["vote"]
_F_TIMEOUT, _F_WITSNAP, _F_RTR = _F["timeout_now"], _F["wit_snap"], _F["rtr"]


class _LazyOut:
    """Field-lazy host view of a ``StepOutput``: ``np.asarray`` per field
    on FIRST access only.  The eager 42-field fetch blocked the host on
    the whole async device step even when the activity mask would prove
    most fields dead (PERF.md's ~80%%-of-wall-clock stall); lanes with no
    replicates never pay for ``s_ent_term`` and friends."""

    __slots__ = ("_out", "_np")

    def __init__(self, out) -> None:
        self._out = out
        self._np: dict[str, np.ndarray] = {}

    def __getitem__(self, f: str) -> np.ndarray:
        v = self._np.get(f)
        if v is None:
            with _capacity.METER.sanctioned("lazy_out"):
                v = np.asarray(getattr(self._out, f))
            self._np[f] = v
        return v


@dataclass
class _StepCtx:
    """Everything the deferred output pass of ONE dispatched step needs,
    captured at dispatch time: staging for the NEXT step rebinds
    ``n._staged_props`` / ``n._staged_ri`` before a pipelined step's
    outputs are retired, so fates and read ctxs must ride the ctx, not
    the node."""

    nodes: dict[int, "KernelNode"]
    fates: dict[int, list]                  # row -> [(entry, origin), ...]
    staged_ri: dict[int, pb.SystemCtx]      # row -> staged ReadIndex ctx
    staged_rows: set[int]
    out: object = None                      # device StepOutput (async)
    dead: set[int] = field(default_factory=set)   # rows removed in flight
    # lifecycle-sampled proposal keys riding this step (dispatch/retire
    # stamps); keys of rows scrubbed in flight stay here harmlessly —
    # stamp() is a no-op once the book's dropped() scrubbed the span
    traced: list = field(default_factory=list)


class KernelNode(Node):
    """A device-resident shard: client surface + books + RSM live on the
    host exactly like ``Node``; the raft state machine lives in a kernel
    lane and is advanced by the owning ``KernelEngine``."""

    def __init__(self, *args, **kw) -> None:
        super().__init__(*args, **kw)
        self.lane: int = -1
        self.engine: KernelEngine | None = None
        # set (under self.mu) when the shard is evicted: every later
        # ingress mutation is redirected to the host-resident successor
        self._moved: Node | None = None
        # payload mirror: log index -> full pb.Entry (device holds terms).
        # On a mesh engine all replicas of a shard share one dict (the
        # in-process form of payload distribution).
        self.mirror: dict[int, pb.Entry] = {}
        # (entry, origin_node) staged into prop lanes this step, by slot —
        # origin tracks whose books own the future (mesh engines forward
        # follower-host proposals onto the leader row)
        self._staged_props: list[tuple[pb.Entry, "KernelNode"]] = []
        self._staged_ri: pb.SystemCtx | None = None
        # remote ReadIndex ctxs forwarded from follower hosts, FIFO
        self._remote_reads: list[tuple[int, pb.SystemCtx]] = []
        # ctx.low -> requesting replica, for remote reads riding the
        # quorum path (answered when the rtr lane lands, steps later)
        self._remote_ri_inflight: dict[int, int] = {}
        self._local_ri_pending: dict[int, pb.SystemCtx] = {}
        self._tick_pending = 0
        self._leader_cache = 0
        self._leader_term_cache = 0
        self._staged_ri_from = 0
        self._committed_cache = 0
        self.applied_since_snapshot = 0

    # the engine drives everything; the loopback step must not touch peer
    def step(self) -> bool:  # pragma: no cover - engine-driven
        return False

    def _post(self, mutate) -> None:
        """Ingress choke point: after eviction, redirect atomically to the
        successor Node so nothing lands in a dead queue (the drain in
        _on_kernel_evict runs under self.mu after _moved is set).  Every
        ingress dirties the lane so the engine's staging pass visits it
        (mark_dirty is lock-free — taking engine.mu here would invert
        the step path's engine.mu -> node.mu order)."""
        with self.mu:
            if self._moved is None:
                mutate(self)
                eng, lane = self.engine, self.lane
                if eng is not None and lane >= 0:
                    eng.mark_dirty(lane)
                return
            target = self._moved
        target._post(mutate)

    def leader_id(self) -> int:
        return self._leader_cache

    def node_term(self) -> int:
        return self._leader_term_cache

    def is_leader(self) -> bool:
        return self._leader_cache == self.replica_id

    def read(self, timeout_ticks: int):
        """Reads enqueue into the book WITHOUT the _post choke point
        (no node-state mutation), so the lane must be dirtied here or
        the staging pass would never pick the batch up — before the
        engine-wide tick broadcast (r5), the per-tick dirty-marking of
        every lane masked this."""
        rs = super().read(timeout_ticks)
        eng, lane = self.engine, self.lane
        if eng is not None and lane >= 0:
            eng.mark_dirty(lane)
        return rs

    def tick(self) -> None:
        """Direct per-lane tick (tests / pre-injection): the NodeHost
        ticker never calls this for engine-registered lanes — it hands
        the whole round to the engine as one pending broadcast
        (KernelEngine.tick_round)."""
        self._tick_pending += 1
        eng, lane = self.engine, self.lane
        if eng is not None and lane >= 0:
            eng.mark_dirty(lane)
        if self._owns_clock:
            self._clock.advance()
        self.gc_books()

    def _take_snapshot(self, req: _SnapshotRequest) -> None:
        """Snapshot for a device-resident shard: the device compacts its
        term ring itself (kernel.py device-side compaction), so the host
        only persists the RSM image + snapshot record and truncates the
        durable log (node.go:739 doSave without the logreader cache)."""
        import os as _os

        from dragonboat_tpu.raftio import EntryInfo, SnapshotInfo  # noqa: F401

        index0 = self.sm.get_last_applied()
        if index0 == 0:
            if req.key:
                self.pending_snapshot.done(req.key,
                                           RequestResultCode.REJECTED)
            return
        path = req.path if req.exported else self._snapshot_path(index0)
        self.fs.makedirs(_os.path.dirname(path) or ".")
        index, term, membership, files = \
            self.sm.save_snapshot_with_files(path)
        ss = pb.Snapshot(
            filepath=path, file_size=self.fs.getsize(path),
            index=index, term=term, membership=membership,
            shard_id=self.shard_id, type=self.sm.sm_type, files=files,
        )
        if req.exported:
            from dragonboat_tpu.tools import write_export_metadata

            write_export_metadata(path, ss, fs=self.fs)
        else:
            self.logdb.save_snapshots([pb.Update(
                shard_id=self.shard_id, replica_id=self.replica_id,
                snapshot=ss)])
            self.events.snapshot_created(SnapshotInfo(
                shard_id=self.shard_id, replica_id=self.replica_id,
                from_=self.replica_id, index=index, term=term))
            overhead = (req.compaction_overhead if req.override_compaction
                        else self.cfg.compaction_overhead)
            compact_to = max(0, index - overhead)
            if compact_to > 0 and not self.cfg.disable_auto_compaction:
                self.logdb.remove_entries_to(
                    self.shard_id, self.replica_id, compact_to)
                self.compacted_to = compact_to
                self.events.log_compacted(EntryInfo(
                    shard_id=self.shard_id, replica_id=self.replica_id,
                    index=compact_to))
        self.applied_since_snapshot = 0
        if req.key:
            self.pending_snapshot.done(
                req.key, RequestResultCode.COMPLETED, snapshot_index=index)

    def _on_config_change_applied(self, entry: pb.Entry, r) -> None:
        """CC apply for a lane: the RSM's membership store is the truth
        and the engine refreshes the device peer book after the apply
        batch; there is no pycore Peer to notify."""
        cc = pb.decode_config_change(entry.cmd)
        if not r.rejected:
            self.membership_changed_cb(cc)
        code = (RequestResultCode.REJECTED if r.rejected
                else RequestResultCode.COMPLETED)
        self.pending_config_change.done(
            entry.key, code, Result(value=entry.index))


@dataclass
class _LaneInit:
    """State captured from a bootstrapped pycore Peer for lane injection."""

    term: int
    vote: int
    committed: int
    applied: int
    snap_index: int
    snap_term: int
    entries: list[pb.Entry]
    peers: list[tuple[int, int]]   # (replica_id, kind)


class KernelEngine:
    """Owns one batched kernel state and every KernelNode mapped onto it."""

    # class-wide: serializes the FIRST jit compile across engines (see
    # step_all; concurrent engine-thread compiles segfaulted XLA:CPU)
    _first_compile_mu = threading.Lock()

    def __init__(self, kp: KP.KernelParams, capacity: int,
                 send_message, events: EventHub | None = None,
                 election_rtt: int = 10, heartbeat_rtt: int = 1,
                 fleet_stats_every: int = 10,
                 pipeline_depth: int = 0,
                 health_top_k: int = 8,
                 health_thresholds=None,
                 invariant_probe: bool = True,
                 capacity_watermark_pct: float = 10.0,
                 capacity_budget_bytes: int = 0) -> None:
        self.kp = kp
        self.capacity = capacity
        self.send_message = send_message
        self.events = events or EventHub()
        self.mu = threading.RLock()
        self.nodes: dict[int, KernelNode] = {}     # lane -> node
        self.by_shard: dict[int, KernelNode] = {}
        self._free = list(range(capacity - 1, -1, -1))
        self.state: ShardState = init_state(
            kp, capacity,
            replica_id=np.ones((capacity,), np.int32),
            peer_ids=np.zeros((capacity, kp.num_peers), np.int32),
            election_timeout=election_rtt,
            heartbeat_timeout=heartbeat_rtt,
        )
        # all lanes start ABSENT: no peers -> non-single, no campaigns
        # (mask: a lane with kind all K_ABSENT and tick never set is inert)
        # per-lane (term, vote, commit) as persisted — an np array so the
        # outputs pass can find changed lanes with one vectorized compare
        # (-1 rows = absent lane: the first real triple always differs)
        self._triple_np = np.full((capacity, 3), -1, np.int64)
        # host mirrors of per-lane leader caches, same reason
        self._lead_np = np.zeros((capacity,), np.int64)
        self._lead_term_np = np.zeros((capacity,), np.int64)
        # lanes with possibly-pending host work (see mark_dirty); its
        # own tiny lock — NOT engine.mu (ingress holds node.mu and the
        # documented order is engine.mu -> node.mu)
        self._dirty: set[int] = set()
        self._dirty_mu = threading.Lock()
        # occupancy vector for the output activity mask (absent lanes
        # must not pass it — the -1 triple sentinel vs device term 0
        # would make every empty lane "active" forever)
        self._occ_np = np.zeros((capacity,), bool)
        # rows that received staged proposals this step (bounds the
        # fate-reset and fate-processing loops)
        self._staged_rows: set[int] = set()
        # nodes removed since the last step (same-thread evictions during
        # staging land here); step_all drains it instead of sweeping all
        # [capacity] rows for vanished registrations
        self._removed_nodes: list[KernelNode] = []
        # first-call guard for the cross-engine compile serialization in
        # step_all (the class-wide _first_compile_mu)
        self._compiled_once = False
        # host mirrors of the device peer books: pids/kinds only change
        # on injection/membership updates, so the output path must not
        # pay a device->host transfer for them every step
        self._kind_np = np.zeros((capacity, kp.num_peers), np.int32)
        self._pid_np = np.zeros((capacity, kp.num_peers), np.int32)
        # admissions queued for the next step's batched injection
        # (lane -> (node, init, pids, kinds)); see _flush_injections
        self._pending_inject: dict[int, tuple] = {}
        # whole-engine tick rounds queued by the host ticker; each step
        # consumes ONE round as a vectorized [G]-bool broadcast (the
        # per-lane Python tick walk was ~25 s/round at 100k lanes).
        # Capped so a long no-node idle cannot bank a burst of rounds
        # that would fast-forward election timers on the first admission
        self._tick_rounds_pending = 0
        self._tick_mu = threading.Lock()
        # persistent staging buffers, zeroed per step (the jitted step
        # needs fixed [capacity] shapes anyway; reallocating every engine
        # iteration would cost ~G*K*E ints of fresh numpy per step).
        # TWO pairs: at pipeline depth 1 staging for step N writes the
        # alternate pair while step N-1 (whose device inbox may alias
        # its numpy staging on CPU backends, and whose buffers are
        # donated) is still in flight; a pair is only rewritten after
        # the step that used it has retired
        # mesh subclasses set _slot_exact_replicas BEFORE super().__init__
        # so hub-fallback staging lands at route()'s exact slot layout
        mesh_r = getattr(self, "_slot_exact_replicas", None)
        self._bufs = tuple(
            (_InboxBuilder(capacity, kp.inbox_cap, kp.msg_entries,
                           mesh_replicas=mesh_r),
             _InputBuilder(capacity, kp.proposal_cap))
            for _ in range(2))
        self._buf_idx = 0
        # aliases to the pair of the most recent dispatch (fleet stats
        # and tests read the staged inbox through these)
        self._inbox_buf, self._input_buf = self._bufs[0]
        # software pipeline: 0 = serial oracle (stage, dispatch, fetch,
        # process in one pass), 1 = retire step N-1 while N is staged,
        # dispatching N through the donating jit entry
        self.pipeline_depth = max(0, min(1, int(pipeline_depth)))
        self._pending_ctx: _StepCtx | None = None
        # pipeline occupancy accounting: a dispatch is "overlapped" when
        # a previous step was still unretired at its staging
        self._pipe_steps = 0
        self._pipe_overlapped = 0
        # step-latency accounting + opt-in jax.profiler capture
        from dragonboat_tpu.tracing import StepTimer, maybe_start_from_env

        self._step_timer = StepTimer(self.events.metrics,
                                     "engine.kernel_step")
        maybe_start_from_env()
        self.events.metrics.set("engine.pipeline.depth", self.pipeline_depth)
        # decimated device-side fleet telemetry (core/fleet.py): every N
        # steps one jitted reduction over the resident state fetches ONE
        # small struct to host; 0 disables
        self.fleet_stats_every = max(0, int(fleet_stats_every))
        self._fleet_countdown = self.fleet_stats_every
        self.last_fleet: dict | None = None
        # standalone engines (no NodeHost) still expose the device-only
        # view; a NodeHost registers its merged host+device view over the
        # same names FIRST in its __init__, so this is a no-op there
        from dragonboat_tpu.core import fleet as _fleet

        _fleet.register_exposition(self.events.metrics.registry,
                                   lambda: self.last_fleet)
        # decimated device-side anomaly classification (core/health.py):
        # rides the fleet countdown; the per-group digest carry stays
        # device resident and only the O(K) report crosses to host.
        # health_top_k=0 disables the pass entirely
        from dragonboat_tpu.core import health as _health

        self.health_top_k = max(0, int(health_top_k))
        self.health_thresholds = (
            _health.HealthThresholds(*health_thresholds)
            if health_thresholds is not None
            else _health.DEFAULT_THRESHOLDS)
        self._health_digest = None      # built lazily at the first tick
        self.last_health: dict | None = None
        self._health_seq = 0            # health ticks taken (flight stamp)
        _health.register_exposition(self.events.metrics.registry,
                                    lambda: self.last_health)
        # decimated protocol-invariant probe (core/invariants.py): the
        # runtime leg of the safety verifier, riding the same fleet
        # countdown.  The prev-field digest carry stays device resident;
        # one O(1) InvariantReport crosses to host.  A violation is
        # ALWAYS a bug, so sightings are sticky (violations_seen) — a
        # transient step-scope violation must not vanish from /healthz
        # at the next clean window
        from dragonboat_tpu.core import invariants as _invariants

        self.invariant_probe = bool(invariant_probe)
        self._inv_digest = None         # built lazily at the first tick
        self.last_invariants: dict | None = None
        self._inv_seq = 0               # probe ticks taken (flight stamp)
        self._inv_violations_seen = 0   # sticky cumulative violation total
        # lanes injected/cleared since the last probe tick: their digest
        # prev-columns describe a DIFFERENT occupant, so the probe must
        # re-seed them (ticks=0) or a fresh shard's lower term would
        # read as a bogus term_monotone violation
        self._inv_dirty: set[int] = set()
        _invariants.register_exposition(self.events.metrics.registry,
                                        lambda: self.last_invariants)
        # capacity rail (dragonboat_tpu/capacity.py): compile telemetry
        # wrappers around every jit entry this engine dispatches, plus
        # decimated device-memory accounting on the fleet cadence
        from dragonboat_tpu import capacity as _capacity

        self.capacity_watermark_pct = float(capacity_watermark_pct)
        self.capacity_budget_bytes = max(0, int(capacity_budget_bytes))
        # the ONE dispatch backend (engine/dispatch.py): subclasses pick
        # a backend through the _make_dispatch seam instead of overriding
        # step-loop internals — the engine-unity lint pass enforces it
        self._dispatch = self._make_dispatch()
        self._cap_entries = self._capacity_entries()
        self.last_capacity: dict | None = None
        self._capacity_seq = 0          # capacity ticks (flight stamp)
        self._capacity_peak = 0         # high-water live tree bytes
        _capacity.register_exposition(self.events.metrics.registry,
                                      lambda: self.last_capacity)

    # -- lane lifecycle ---------------------------------------------------

    def add_shard(self, node: KernelNode, init: _LaneInit) -> None:
        """Inject a bootstrapped shard into a free lane.  The lane write
        happens under the engine lock: a concurrent step must never run
        between registration and injection (it would write back a stepped
        pre-injection state, clobbering the lane)."""
        with self.mu:
            if not self._free:
                raise RuntimeError("kernel engine is at capacity")
            lane = self._free.pop()
            node.lane = lane
            node.engine = self
            self.nodes[lane] = node
            self.by_shard[node.shard_id] = node
            self._inject(lane, node, init)

    def remove_shard(self, shard_id: int) -> KernelNode | None:
        with self.mu:
            node = self.by_shard.pop(shard_id, None)
            if node is None:
                return None
            self.nodes.pop(node.lane, None)
            self._free.append(node.lane)
            self._clear_lane(node.lane)
            self._removed_nodes.append(node)
        return node

    def close(self) -> None:
        """Engine teardown.  Flushes a DRAGONBOAT_TPU_TRACE_DIR-armed
        profiler capture while the JAX backend is unambiguously alive —
        relying on atexit for it races interpreter/backend shutdown and
        can leave the trace dir empty (a user-started ``start_trace``
        capture is deliberately left to its owner)."""
        stop_env_trace()

    def _inject(self, lane: int, node: KernelNode, init: _LaneInit) -> None:
        """Queue one lane injection; the next ``step_all`` flushes every
        queued lane in ONE vectorized state update.  The eager form was
        ~30 full-[capacity] array copies PER admission — O(n·capacity)
        total, the first structure to fall over at 100k groups.  Host
        bookkeeping (kind cache, payload mirror, writeback triple) is
        done here so non-state readers see the shard immediately."""
        kp = self.kp
        pids = np.zeros((kp.num_peers,), np.int32)
        kinds = np.zeros((kp.num_peers,), np.int32)
        for i, (rid, kind) in enumerate(init.peers[:kp.num_peers]):
            pids[i], kinds[i] = rid, kind
        self._kind_np[lane] = kinds
        self._pid_np[lane] = pids
        for e in init.entries:
            node.mirror[e.index] = e
        self._triple_np[lane] = (init.term, init.vote, init.committed)
        self._lead_np[lane] = 0
        self._lead_term_np[lane] = 0
        self._occ_np[lane] = True
        self._pending_inject[lane] = (node, init, pids, kinds)
        self._inv_dirty.add(lane)
        self.mark_dirty(lane)

    def _flush_injections(self) -> None:
        """One ``.at[lanes].set`` per state field for every admission
        queued since the last step — O(capacity + n) instead of
        O(n·capacity)."""
        if not self._pending_inject:
            return
        kp = self.kp
        items = sorted(self._pending_inject.items())
        self._pending_inject = {}
        n = len(items)
        lanes_np = np.array([g for g, _ in items], np.int32)
        f32 = {k: np.zeros((n,), np.int32) for k in (
            "replica_id", "seed", "rand_timeout", "e_timeout", "h_timeout",
            "role", "term", "vote", "applied", "snap_index", "snap_term",
            "last", "committed")}
        fb = {k: np.zeros((n,), bool) for k in ("check_quorum", "pre_vote",
                                                "quiesce_on")}
        pid_rows = np.zeros((n, kp.num_peers), np.int32)
        kind_rows = np.zeros((n, kp.num_peers), np.int32)
        lt_rows = np.zeros((n, kp.log_cap), np.int32)
        lcc_rows = np.zeros((n, kp.log_cap), bool)
        for j, (lane, (node, init, pids, kinds)) in enumerate(items):
            pid_rows[j], kind_rows[j] = pids, kinds
            for e in init.entries:
                lt_rows[j, e.index & (kp.log_cap - 1)] = e.term
                lcc_rows[j, e.index & (kp.log_cap - 1)] = \
                    e.is_config_change()
            last = init.entries[-1].index if init.entries \
                else init.snap_index
            role = KP.FOLLOWER
            my_kind = dict(init.peers).get(node.replica_id, KP.K_VOTER)
            if my_kind == KP.K_NON_VOTING:
                role = KP.NON_VOTING
            elif my_kind == KP.K_WITNESS:
                role = KP.WITNESS
            cfg = node.cfg
            # per-(shard, replica) PRNG stream: lanes injected on
            # different hosts must NOT share election-timeout sequences
            # or symmetric campaigns livelock (randomizedElectionTimeout,
            # raft.go:659)
            seed = int(KP.splitmix32(
                (node.shard_id * 2654435761 + node.replica_id * 40503)
                & 0xFFFFFFFF)) & 0x7FFFFFFF
            f32["replica_id"][j] = node.replica_id
            f32["seed"][j] = seed
            f32["rand_timeout"][j] = KP.randomized_timeout(
                seed, 0, cfg.election_rtt)
            f32["e_timeout"][j] = cfg.election_rtt
            f32["h_timeout"][j] = max(1, cfg.heartbeat_rtt)
            fb["check_quorum"][j] = cfg.check_quorum
            fb["pre_vote"][j] = cfg.pre_vote
            fb["quiesce_on"][j] = cfg.quiesce
            f32["role"][j] = role
            f32["term"][j] = init.term
            f32["vote"][j] = init.vote
            f32["applied"][j] = init.applied
            f32["snap_index"][j] = init.snap_index
            f32["snap_term"][j] = init.snap_term
            f32["last"][j] = last
            f32["committed"][j] = init.committed
        s = self.state
        with _capacity.METER.sanctioned("inject_up"):
            lanes = jnp.asarray(lanes_np)
            A = {k: jnp.asarray(v) for k, v in {**f32, **fb}.items()}

            def put(arr, vals):
                # route sub-32-bit scatters through int32: non-uniform-
                # index scatters on bool operands silently drop writes on
                # TPU past ~3k rows (the _set1 miscompile, core/kernel.py)
                # — an admission batch is exactly that shape
                if arr.dtype == jnp.bool_:
                    vals_i = jnp.asarray(vals).astype(jnp.int32)
                    return (arr.astype(jnp.int32).at[lanes].set(vals_i)
                            .astype(bool))
                return arr.at[lanes].set(vals)

            last_v = A["last"]
            self.state = s._replace(
                replica_id=put(s.replica_id, A["replica_id"]),
                seed=put(s.seed, A["seed"]),
                rand_timeout=put(s.rand_timeout, A["rand_timeout"]),
                rand_counter=put(s.rand_counter, 0),
                e_timeout=put(s.e_timeout, A["e_timeout"]),
                h_timeout=put(s.h_timeout, A["h_timeout"]),
                check_quorum=put(s.check_quorum, A["check_quorum"]),
                pre_vote=put(s.pre_vote, A["pre_vote"]),
                role=put(s.role, A["role"]),
                term=put(s.term, A["term"]),
                vote=put(s.vote, A["vote"]),
                leader=put(s.leader, 0),
                applied=put(s.applied, A["applied"]),
                e_tick=put(s.e_tick, 0),
                h_tick=put(s.h_tick, 0),
                pending_cc=put(s.pending_cc, False),
                ltt=put(s.ltt, 0),
                is_ltt=put(s.is_ltt, False),
                pid=put(s.pid, jnp.asarray(pid_rows)),
                kind=put(s.kind, jnp.asarray(kind_rows)),
                match=put(s.match, 0),
                next=put(s.next, (last_v + 1)[:, None]),
                pstate=put(s.pstate, KP.R_RETRY),
                active=put(s.active, False),
                psnap=put(s.psnap, 0),
                vresp=put(s.vresp, False),
                vgrant=put(s.vgrant, False),
                lt=put(s.lt, jnp.asarray(lt_rows)),
                lcc=put(s.lcc, jnp.asarray(lcc_rows)),
                snap_index=put(s.snap_index, A["snap_index"]),
                snap_term=put(s.snap_term, A["snap_term"]),
                last=put(s.last, last_v),
                committed=put(s.committed, A["committed"]),
                processed=put(s.processed, A["applied"]),
                stable=put(s.stable, last_v),
                ri_head=put(s.ri_head, 0),
                ri_count=put(s.ri_count, 0),
                needs_host=put(s.needs_host, False),
                quiesce_on=put(s.quiesce_on, A["quiesce_on"]),
                idle_tick=put(s.idle_tick, 0),
                quiesced=put(s.quiesced, False),
                quiesce_epoch=put(s.quiesce_epoch, 0),
            )

    def _clear_lane(self, lane: int) -> None:
        self._inv_dirty.add(lane)
        if self._pending_inject.pop(lane, None) is not None:
            # evicted before its injection ever flushed: the lane state
            # was never written, so there is nothing to clear on device
            self._kind_np[lane] = KP.K_ABSENT
            self._pid_np[lane] = 0
            self._triple_np[lane] = -1
            self._occ_np[lane] = False
            return
        s = self.state
        self.state = s._replace(
            kind=s.kind.at[lane].set(KP.K_ABSENT),
            pid=s.pid.at[lane].set(0),
            needs_host=s.needs_host.at[lane].set(False),
            # a vacated lane must not linger in the fleet quiesced count
            quiesce_on=s.quiesce_on.at[lane].set(False),
            quiesced=s.quiesced.at[lane].set(False),
        )
        self._kind_np[lane] = KP.K_ABSENT
        self._pid_np[lane] = 0
        self._triple_np[lane] = -1
        self._occ_np[lane] = False

    def update_lane_membership(self, node: KernelNode) -> None:
        """Re-derive the lane's peer book from the RSM membership (host
        applies config changes; the device book follows).  A membership
        larger than the fixed [P] peer book cannot be modeled on device —
        quorum over a truncated book would be unsafe — so the shard is
        evicted to the host engine instead."""
        m = node.sm.get_membership()
        kp = self.kp
        total = len(m.addresses) + len(m.non_votings) + len(m.witnesses)
        if total > kp.num_peers:
            self._evict(node, reason=f"membership {total} > "
                                     f"kernel peer book {kp.num_peers}")
            return
        pids = np.zeros((kp.num_peers,), np.int32)
        kinds = np.zeros((kp.num_peers,), np.int32)
        i = 0
        for rid in sorted(m.addresses):
            if i < kp.num_peers:
                pids[i], kinds[i] = rid, KP.K_VOTER
                i += 1
        for rid in sorted(m.non_votings):
            if i < kp.num_peers:
                pids[i], kinds[i] = rid, KP.K_NON_VOTING
                i += 1
        for rid in sorted(m.witnesses):
            if i < kp.num_peers:
                pids[i], kinds[i] = rid, KP.K_WITNESS
                i += 1
        g = node.lane
        s = self.state
        with _capacity.METER.sanctioned("membership_up"):
            jp, jk = jnp.asarray(pids), jnp.asarray(kinds)
        self.state = s._replace(
            pid=s.pid.at[g].set(jp),
            kind=s.kind.at[g].set(jk),
            # the applied CC releases the one-in-flight gate (pycore
            # add_node/add_non_voting/... clear pending_config_change on
            # apply; without this a lane accepts exactly ONE config
            # change in its lifetime and drops every later one)
            pending_cc=s.pending_cc.at[g].set(False),
        )
        self._kind_np[g] = kinds
        self._pid_np[g] = pids

    # -- the step ---------------------------------------------------------

    def tick_round(self) -> None:
        """Queue one tick round for EVERY registered lane (called once
        per host tick interval; consumed in step_all as one vectorized
        broadcast)."""
        with self._tick_mu:
            if self._tick_rounds_pending < 8:
                self._tick_rounds_pending += 1

    def mark_dirty(self, lane: int) -> None:
        """Flag a lane for the next staging pass.  Guarded by its own
        lock rather than engine.mu (ingress already holds node.mu, and
        the step path's order is engine.mu -> node.mu): a bare set.add
        could land in a set the step thread just swapped out and be
        silently dropped."""
        with self._dirty_mu:
            self._dirty.add(lane)

    def step_all(self) -> bool:
        """One engine iteration; returns True if any lane had work
        (messages, ticks, proposals, reads) or an in-flight pipelined
        step was retired.  Only DIRTY lanes stage — the full-scan form
        cost 16 µs/lane of Python per step (1.6 s at 100k lanes) whether
        or not anything was pending.  Runs under the engine lock: lane
        injection/eviction and the device state update must not
        interleave with a step.

        Pipeline order at depth 1 (every part of it is load-bearing):
        (1) stage step N into the alternate buffer pair — host marshaling
        overlaps the device compute of step N-1; (2) retire step N-1's
        deferred outputs — this is the first point the host blocks on
        the device, and it must run BEFORE (3) dispatches step N with
        donated buffers, because retiring reads previous-state leaves
        (lt rows, the wit-snap floor) that donation hands to XLA."""
        with self.mu:
            nodes = dict(self.nodes)
            if not nodes:
                if self._pending_ctx is not None:
                    # every lane vanished with a step in flight: fail the
                    # removed nodes' staged futures, then retire the step
                    # so nothing hangs on an answer that cannot land
                    removed, self._removed_nodes = self._removed_nodes, []
                    for n in removed:
                        if not self._is_registered(n):
                            self._scrub_pending_ctx(n)
                            self._drop_staged_fates(n)
                    ctx, self._pending_ctx = self._pending_ctx, None
                    with annotate("kernel_engine.process_outputs"):
                        self._process_outputs(ctx)
                    return True
                return False
            self._flush_injections()
            inbox, inp = self._bufs[self._buf_idx]
            self._inbox_buf, self._input_buf = inbox, inp
            inbox.reset()
            inp.reset()
            had_work = False

            # swap out the dirty set; arrivals during this step land in
            # the fresh set and stage next iteration
            with self._dirty_mu:
                dirty, self._dirty = self._dirty, set()
            staged = [(g, nodes[g]) for g in sorted(dirty) if g in nodes]
            # staging may target OTHER rows' prop slots (mesh engines
            # forward follower-host proposals to the leader row); only
            # rows recorded as prop targets can hold stale fates.  The
            # pending ctx (if any) captured the OLD list objects, so the
            # rebind here cannot lose in-flight fates
            self._slot_cursor: dict[int, int] = {}
            for g in self._staged_rows:
                n = nodes.get(g)
                if n is not None:
                    n._staged_props = []
            self._staged_rows = set()
            for g, n in staged:
                if self._stage_lane(g, n, inbox, inp):
                    had_work = True
            # consume one queued engine-wide tick round: every
            # registered lane ticks via ONE vectorized bool write —
            # no per-lane Python, no dirty-marking the whole batch
            with self._tick_mu:
                tick_round = self._tick_rounds_pending > 0
                if tick_round:
                    self._tick_rounds_pending -= 1
            if tick_round:
                lanes = np.fromiter(nodes.keys(), np.int64, len(nodes))
                inp._tick[lanes] = True
                had_work = True
            # an eviction while staging (InstallSnapshot; whole-GROUP on a
            # mesh engine) may remove rows staged EARLIER in this loop —
            # drop them, failing any proposals forwarded onto them so the
            # origin futures fail fast instead of timing out.  Removals
            # are drained from the explicit log remove_shard keeps (the
            # full [capacity] registration sweep this replaces was a fixed
            # ~16 µs/lane of Python per step at 100k lanes).  An in-flight
            # pipelined step is scrubbed FIRST: its captured fates are the
            # removed node's un-reset lists, and the scrub empties them so
            # _drop_staged_fates cannot fail the same futures twice
            removed, self._removed_nodes = self._removed_nodes, []
            for n in removed:
                if self._is_registered(n):
                    continue  # re-admitted since removal
                self._scrub_pending_ctx(n)
                self._drop_staged_fates(n)
                if nodes.get(n.lane) is n:
                    nodes.pop(n.lane)
            if not (had_work or self._device_pending()):
                if self._pending_ctx is not None:
                    # nothing new to dispatch — drain the pipeline: the
                    # in-flight step's outputs still owe applies, futures
                    # and events, and retiring re-dirties its lanes so
                    # follow-on work stages next iteration
                    ctx, self._pending_ctx = self._pending_ctx, None
                    with annotate("kernel_engine.process_outputs"):
                        self._process_outputs(ctx)
                    return True
                return False

            ctx = _StepCtx(
                nodes=nodes,
                fates={g: nodes[g]._staged_props
                       for g in self._staged_rows if g in nodes},
                staged_ri={g: n._staged_ri for g, n in staged
                           if n._staged_ri is not None},
                staged_rows=set(self._staged_rows),
            )
            if lifecycle.TRACER.enabled:
                ctx.traced = [e.key for fl in ctx.fates.values()
                              for e, _origin in fl
                              if e.key and lifecycle.TRACER.sampled(e.key)]
            with self._step_timer.measure():
                overlapped = self._pending_ctx is not None
                if overlapped:
                    # retire step N-1 BEFORE the donating dispatch of N
                    pending, self._pending_ctx = self._pending_ctx, None
                    with annotate("kernel_engine.process_outputs"):
                        self._process_outputs(pending)
                with annotate("kernel_engine.step"):
                    if not self._compiled_once:
                        # serialize FIRST calls across engines (incl. the
                        # mesh override): concurrent jit compiles from
                        # several engine threads have segfaulted XLA:CPU
                        # (2026-07-31); once the executable is cached the
                        # lock is never touched again
                        with KernelEngine._first_compile_mu:
                            state, out = self._kernel_call(inbox, inp)
                        self._compiled_once = True
                    else:
                        state, out = self._kernel_call(inbox, inp)
                self.state = state
                ctx.out = out
                for k in ctx.traced:
                    lifecycle.TRACER.stamp(k, lifecycle.STAGE_DISPATCH)
                self._pipe_steps += 1
                if self.pipeline_depth > 0:
                    # defer the fetch: the outputs are consumed one step
                    # late, overlapping device step N+1 with this retire
                    self._pending_ctx = ctx
                    self._buf_idx ^= 1
                    if overlapped:
                        self._pipe_overlapped += 1
                    m = self.events.metrics
                    m.inc("engine.pipeline.steps")
                    if overlapped:
                        m.inc("engine.pipeline.overlapped")
                    m.set("engine.pipeline.occupancy_pct",
                          100 * self._pipe_overlapped
                          // max(1, self._pipe_steps))
                else:
                    with annotate("kernel_engine.process_outputs"):
                        self._process_outputs(ctx)
            if self.fleet_stats_every > 0:
                self._fleet_countdown -= 1
                if self._fleet_countdown <= 0:
                    self._fleet_countdown = self.fleet_stats_every
                    self._collect_fleet_stats()
                    if self.health_top_k > 0:
                        self._collect_health()
                    if self.invariant_probe:
                        self._collect_invariants()
                    self._collect_capacity()
            return True

    def _is_registered(self, n: KernelNode) -> bool:
        # identity, not membership: with a deferred (pipelined) output
        # pass the same shard id can be re-admitted as a NEW node while
        # the old one's step is still in flight
        return self.by_shard.get(n.shard_id) is n

    @staticmethod
    def _fail_fates(fates) -> None:
        for entry, origin in fates:
            if entry.is_config_change():
                origin.pending_config_change.done(
                    entry.key, RequestResultCode.DROPPED)
            else:
                origin._rl_release(entry.key)
                origin.pending_proposals.dropped(entry.key)

    def _drop_staged_fates(self, n: KernelNode) -> None:
        self._fail_fates(n._staged_props)
        n._staged_props = []

    def _scrub_pending_ctx(self, n: KernelNode) -> None:
        """Remove a dead node's rows from the in-flight step ctx: fail
        its staged-proposal futures now (the retire pass will skip the
        row) rather than letting them time out against a node whose
        books no longer exist."""
        ctx = self._pending_ctx
        if ctx is None or ctx.nodes.get(n.lane) is not n:
            return
        fates = ctx.fates.pop(n.lane, None)
        if fates:
            if n._staged_props is fates:
                n._staged_props = []
            self._fail_fates(fates)
        ctx.staged_ri.pop(n.lane, None)
        ctx.dead.add(n.lane)

    def _make_dispatch(self):
        """Dispatch-backend factory — the sanctioned seam an engine
        subclass uses to change WHERE the step runs (serial jit vs the
        parallel/ici.py shard_map path) without growing a second step
        loop.  Called once at the end of __init__."""
        from dragonboat_tpu.engine.dispatch import SerialDispatch

        # bind THIS module's globals at construction: chaos tests swap
        # kernel_step/kernel_step_donated for mutated kernels here
        return SerialDispatch(self.kp, kernel_step, kernel_step_donated)

    def _device_pending(self) -> bool:
        """True while the dispatch backend carries undelivered messages
        between steps (the mesh backend's device-resident inbox); the
        serial backend re-stages from host queues and never does."""
        return self._dispatch.pending()

    def _fleet_inbox_from(self):
        """[G, K] sender ids feeding the inbox-occupancy histogram: the
        backend picks the host-staged builder or its carried box."""
        return self._dispatch.inbox_from(self._inbox_buf)

    def _collect_fleet_stats(self) -> None:
        """Decimated fleet telemetry: one jitted reduction over the
        resident state, one small struct fetched to host (core/fleet.py).
        Runs under engine.mu right after a step, so the state it reads is
        exactly the state the step produced."""
        from dragonboat_tpu.core import fleet as _fleet

        with _capacity.METER.sanctioned("fleet_down"):
            stats = self._cap_entries["fleet_stats"](
                self.state, self._fleet_inbox_from())
            self.last_fleet = _fleet.stats_to_dict(stats)

    def _make_health_digest(self):
        """Fresh all-zero digest matching the engine's lane geometry,
        placed by the dispatch backend (the mesh backend shards it
        along G like the state it derives from)."""
        from dragonboat_tpu.core import health as _health

        return self._dispatch.shard(_health.empty_digest(self.capacity))

    def _collect_health(self) -> None:
        """Decimated anomaly classification (core/health.py), on the
        same cadence (and under the same engine.mu post-step window) as
        ``_collect_fleet_stats``.  The digest carry never leaves the
        device; one O(K) HealthReport is fetched.  Class-count edges
        (0 -> nonzero and back) are recorded as flight-recorder
        anomaly_raised/anomaly_cleared events stamped with the engine's
        health-tick sequence — never the wall clock."""
        from dragonboat_tpu import flight
        from dragonboat_tpu.core import health as _health

        if self._health_digest is None:
            self._health_digest = self._make_health_digest()
        with _capacity.METER.sanctioned("health_down"):
            report, self._health_digest = self._cap_entries["fleet_health"](
                self.state, self._fleet_inbox_from(), self._health_digest,
                thresholds=self.health_thresholds, k=self.health_top_k)
            cur = _health.report_to_dict(report)
        prev = self.last_health
        self._health_seq += 1
        self.last_health = cur
        prev_counts = prev["class_count"] if prev else {}
        for cls, n in cur["class_count"].items():
            was = prev_counts.get(cls, 0)
            if n > 0 and was == 0:
                flight.record(flight.ANOMALY_RAISED, cls=cls, count=n,
                              tick=self._health_seq)
            elif n == 0 and was > 0:
                flight.record(flight.ANOMALY_CLEARED, cls=cls,
                              tick=self._health_seq)

    def _make_invariant_digest(self):
        """Fresh all-zero invariant digest matching the engine's lane
        geometry, placed by the dispatch backend (same sharding story
        as the health digest)."""
        from dragonboat_tpu.core import invariants as _invariants

        return self._dispatch.shard(
            _invariants.empty_digest(self.capacity))

    def _collect_invariants(self) -> None:
        """Decimated protocol-invariant probe (core/invariants.py), on
        the same cadence (and under the same engine.mu post-step window)
        as ``_collect_fleet_stats``.  Lanes whose occupant changed since
        the last probe tick are re-seeded (ticks=0) so step-scoped
        invariants never compare across occupants.  A 0 -> nonzero
        violation edge is recorded as an ``invariant_violation`` flight
        event stamped with the probe-tick sequence — never the wall
        clock."""
        from dragonboat_tpu import flight
        from dragonboat_tpu.core import invariants as _invariants

        if self._inv_digest is None:
            self._inv_digest = self._make_invariant_digest()
        with _capacity.METER.sanctioned("invariants_down"):
            if self._inv_dirty:
                lanes = jnp.asarray(
                    np.array(sorted(self._inv_dirty), np.int32))
                self._inv_dirty.clear()
                d = self._inv_digest
                self._inv_digest = d._replace(
                    ticks=d.ticks.at[lanes].set(0))
            report, self._inv_digest = self._cap_entries[
                "check_invariants"](self.state, self._inv_digest)
            cur = _invariants.report_to_dict(report)
        prev = self.last_invariants
        self._inv_seq += 1
        self._inv_violations_seen += cur["total"]
        cur["violations_seen"] = self._inv_violations_seen
        self.last_invariants = cur
        was = prev["total"] if prev else 0
        if cur["total"] > 0 and was == 0:
            first = cur["first"] or {}
            flight.record(flight.INVARIANT_VIOLATION,
                          total=cur["total"],
                          lane=first.get("lane", -1),
                          invariants=first.get("invariants", []),
                          tick=self._inv_seq)

    def _capacity_entries(self) -> dict:
        """Compile-telemetry wrappers for every jit entry this engine
        dispatches: the backend's step entries (serial step/step_donated
        or the mesh serve pair) plus the shared telemetry reductions.
        Each engine wraps independently (own counters): a first compile
        at THIS engine's geometry is never mistaken for a retrace of
        another engine sharing the same jitted function."""
        from dragonboat_tpu import capacity as _capacity
        from dragonboat_tpu.core import fleet as _fleet
        from dragonboat_tpu.core import health as _health
        from dragonboat_tpu.core import invariants as _invariants

        entries = dict(self._dispatch.entries)
        entries.update({
            "fleet_stats": _capacity.TRACKER.wrap(
                "fleet_stats", _fleet.fleet_stats),
            "fleet_health": _capacity.TRACKER.wrap(
                "fleet_health", _health.fleet_health),
            "check_invariants": _capacity.TRACKER.wrap(
                "check_invariants", _invariants.check_invariants),
        })
        return entries

    def _capacity_trees(self) -> tuple:
        """Device-resident trees this engine keeps alive between steps
        (the mesh backend adds its carried inbox)."""
        return (self.state, self._health_digest, self._inv_digest) \
            + self._dispatch.resident_trees()

    def _capacity_model_classes(self) -> tuple:
        """Contract classes resident on device for this engine's
        geometry: the serial backend re-stages its inbox from host each
        step, so only state + digests persist; the mesh backend carries
        its Inbox."""
        return ("ShardState", "HealthDigest", "InvariantDigest") \
            + self._dispatch.resident_classes()

    def _collect_capacity(self) -> None:
        """Decimated capacity accounting, riding the fleet cadence under
        the same engine.mu post-step window: live bytes of the resident
        trees (shape-derived — no device sync), allocator stats where
        the backend reports them, the contracts capacity model at this
        geometry, and the compile counters.  The memory_pressure
        watermark crossing is recorded as an edge-triggered flight event
        stamped with the capacity tick — never the wall clock."""
        from dragonboat_tpu import capacity as _capacity
        from dragonboat_tpu import flight

        live = _capacity.measure_tree_bytes(*self._capacity_trees())
        self._capacity_seq += 1
        self._capacity_peak = max(self._capacity_peak, live)
        prev = self.last_capacity
        cur = _capacity.engine_snapshot(
            self.kp, self.capacity, live, self._capacity_peak,
            {name: w.stats() for name, w in self._cap_entries.items()},
            budget_bytes=self.capacity_budget_bytes,
            watermark_pct=self.capacity_watermark_pct,
            ticks=self._capacity_seq,
            classes=self._capacity_model_classes())
        self.last_capacity = cur
        was = bool(prev and prev["memory_pressure"])
        if cur["memory_pressure"] and not was:
            flight.record(flight.MEMORY_PRESSURE,
                          bytes_in_use=cur["bytes_in_use"],
                          budget_bytes=cur["budget_bytes"],
                          headroom_pct=cur["headroom_pct"],
                          tick=self._capacity_seq)

    def health_row(self, lane: int) -> dict:
        """One lane's drill-down row (NodeHost.shard_info): an O(1)
        dynamic_index fetch of device scalars — the full ShardState is
        never materialized on host."""
        from dragonboat_tpu.core import health as _health

        with self.mu:
            if self._health_digest is None:
                self._health_digest = self._make_health_digest()
            with _capacity.METER.sanctioned("health_row"):
                row = _health.shard_row(
                    self.state, self._fleet_inbox_from(),
                    self._health_digest, np.int32(lane),
                    thresholds=self.health_thresholds)
                return _health.row_to_dict(row)

    def _kernel_call(self, inbox: _InboxBuilder, inp: _InputBuilder):
        # depth > 0 routes through the backend's donating entry: XLA
        # reuses the state/inbox/input buffers in place of per-step
        # fresh allocations.  After a donating dispatch the host must
        # not read the passed-in state again — step_all's
        # retire-before-dispatch order upholds that on BOTH backends
        return self._dispatch.dispatch(
            self.state, inbox, inp, donate=self.pipeline_depth > 0)

    # -- staging ----------------------------------------------------------

    def _stage_lane(self, g: int, n: KernelNode, inbox: _InboxBuilder,
                    inp: _InputBuilder) -> bool:
        work = False
        with n.mu:
            msgs, n.incoming_msgs = n.incoming_msgs, []
            props, n.incoming_proposals = n.incoming_proposals, []
            cc_entry, n.config_change_entry = n.config_change_entry, None
            transfer, n.transfer_target = n.transfer_target, None
            ss_req, n.snapshot_request = n.snapshot_request, None
            lq, n.log_query_range = n.log_query_range, None
            compact_key, n.compaction_request_key = (
                n.compaction_request_key, None)
            ticks, n._tick_pending = n._tick_pending, 0
            # sticky transfer lease: the kernel aborts an armed transfer
            # at its next check-quorum round (core/kernel.py abort_tr),
            # which under apply backpressure fires before the transferee
            # can catch up — a one-shot staging then loses the request
            # forever.  Re-arm every step while the transfer future is
            # live; the re-arm is a no-op while ltt is set, and the book
            # timeout (pending_transfer.gc) bounds the lease
            if transfer is None and n._transfer_awaiting is not None:
                if n.pending_transfer.outstanding is not None:
                    transfer = n._transfer_awaiting[0]
                else:
                    n._transfer_awaiting = None    # timed out: lease over

        # an InstallSnapshot forces eviction — restore everything drained
        # so the successor Node inherits it intact
        if any(m.type == MT.INSTALL_SNAPSHOT for m in msgs):
            with n.mu:
                n.incoming_msgs = (
                    [m for m in msgs if m.type != MT.INSTALL_SNAPSHOT]
                    + n.incoming_msgs)
                n.incoming_proposals = props + n.incoming_proposals
                n.config_change_entry = n.config_change_entry or cc_entry
                n.transfer_target = n.transfer_target or transfer
                n.snapshot_request = n.snapshot_request or ss_req
                n.log_query_range = n.log_query_range or lq
                n.compaction_request_key = (n.compaction_request_key
                                            or compact_key)
            self._evict(n, reason="install-snapshot",
                        carry=[m for m in msgs
                               if m.type == MT.INSTALL_SNAPSHOT])
            return True

        # host-side ops that never touch the device
        if lq is not None:
            self._answer_log_query(n, lq)
        if compact_key is not None:
            n._process_compaction(compact_key)

        requeue: list[pb.Message] = []
        for m in msgs:
            if m.type == MT.LOCAL_TICK:
                ticks += 1
            elif m.type == MT.READ_INDEX:
                # a follower host forwarded a read (hint carries its ctx)
                n._remote_reads.append(
                    (m.from_, pb.SystemCtx(low=m.hint, high=m.hint_high)))
            elif m.type == MT.READ_INDEX_RESP:
                n._local_ri_pending.pop(m.hint, None)
                n.pending_reads.add_ready(
                    pb.SystemCtx(low=m.hint, high=m.hint_high), m.log_index)
                n.pending_reads.applied(n.sm.get_last_applied())
            elif m.type in _KERNEL_MTYPES:
                if not inbox.add(g, m, n):
                    requeue.append(m)
                work = True
            # other local/quiesce messages: ignored on the kernel path
        if requeue:
            with n.mu:
                n.incoming_msgs = requeue + n.incoming_msgs

        # proposals -> prop lanes (payload staged by slot, fate correlated
        # in _process_outputs)
        if cc_entry is not None or props:
            self._stage_props(g, n, inp, cc_entry, props)
            work = True

        # one batched ReadIndex ctx per step: prefer a forwarded remote
        # read, else the local batch (node.go:1296)
        n._staged_ri = None
        ri_from = 0
        if n._remote_reads:
            ri_from, ctx = n._remote_reads.pop(0)
            n._staged_ri = ctx
            n._remote_ri_inflight[ctx.low] = ri_from
            inp.read(g, ctx)
            work = True
        else:
            ctx = n.pending_reads.peep()
            if ctx is not None:
                if n.is_leader() or len(self._peers_of(n)) == 1:
                    n._staged_ri = ctx
                    n._local_ri_pending[ctx.low] = ctx
                    inp.read(g, ctx)
                elif n._leader_cache != 0:
                    # forward to the leader host (raft.go ReadIndex
                    # leader forwarding)
                    n._local_ri_pending[ctx.low] = ctx
                    n.send_message(pb.Message(
                        type=MT.READ_INDEX, from_=n.replica_id,
                        to=n._leader_cache, shard_id=n.shard_id,
                        hint=ctx.low, hint_high=ctx.high))
                else:
                    n.pending_reads.dropped(ctx)
                work = True
        n._staged_ri_from = ri_from

        if transfer is not None:
            inp.transfer(g, transfer)
            work = True
        if ss_req is not None:
            self._take_lane_snapshot(n, ss_req)
        if ticks:
            inp.tick(g)
            work = True
        inp.applied(g, n.sm.get_last_applied())
        # anything left queued (inbox overflow requeues, extra remote
        # reads, an unserved local read batch) re-stages next step
        with n.mu:
            residual = bool(n.incoming_msgs or n.incoming_proposals
                            or n._remote_reads
                            or n.config_change_entry is not None
                            or n.transfer_target is not None
                            or n._transfer_awaiting is not None
                            or n.snapshot_request is not None
                            or n.log_query_range is not None
                            or n.compaction_request_key is not None
                            or n._tick_pending)
        # non-destructive batch probe: peep() here would move the batch
        # under a fresh ctx that nothing ever stages — its readers would
        # sit in pending until the timeout GC fires
        if residual or n.pending_reads.batching:
            self._dirty.add(g)
        return work

    def _prop_target(self, n: KernelNode) -> tuple[int, KernelNode]:
        """(row, node) whose prop lanes this node's proposals stage into.
        The single-device engine always proposes on its own lane (the
        kernel drops non-leader proposals and the client retries); mesh
        engines override to forward to the group's leader row."""
        return n.lane, n

    def _stage_props(self, g: int, n: KernelNode, inp: _InputBuilder,
                     cc_entry, props) -> None:
        """Stage cc + proposals into prop slots, remembering the origin
        node per slot so fates (drop/mirror) land on the right books."""
        tg, tn = self._prop_target(n)
        self._staged_rows.add(tg)
        slot = self._slot_cursor.get(tg, 0)
        if cc_entry is not None:
            if slot < inp.B:
                inp.prop(tg, slot, True)
                tn._staged_props.append((cc_entry, n))
                slot += 1
            else:
                with n.mu:
                    n.config_change_entry = n.config_change_entry or cc_entry
        for e in props:
            if slot >= inp.B:
                with n.mu:
                    n.incoming_proposals.append(e)
                continue
            inp.prop(tg, slot, False)
            tn._staged_props.append((e, n))
            if e.key:
                lifecycle.TRACER.stamp(e.key, lifecycle.STAGE_STAGE)
            slot += 1
        self._slot_cursor[tg] = slot

    def _peers_of(self, n: KernelNode) -> dict[int, str]:
        m = n.sm.get_membership()
        return {**m.addresses, **m.non_votings, **m.witnesses}

    # -- output processing -------------------------------------------------

    def _process_outputs(self, ctx: _StepCtx) -> None:
        """Retire one dispatched step: resolve proposal fates, emit
        messages, persist, apply, complete reads, fire events.  Serial
        mode calls this inline; pipelined mode one step late (the ctx
        carries the fates/read ctxs that staging has since rebound).

        The fetch is MASKED: a [G, C] per-class activity matrix (one
        tiny jitted reduction, core/kernel.py output_row_flags) plus the
        cheap [G] scalars decide which lanes and which message classes
        are live, and only those fields are pulled to host (_LazyOut) —
        the eager 42-field np.asarray sweep was ~80% of step wall clock
        at 20k lanes."""
        nodes, out = ctx.nodes, ctx.out
        for k in ctx.traced:
            lifecycle.TRACER.stamp(k, lifecycle.STAGE_RETIRE)
        with _capacity.METER.sanctioned("output_flags"):
            flags = np.asarray(output_row_flags(out))
        # the dispatch backend derives drain-pending from the same flags
        # (MeshDispatch dropped its per-step pending-scalar download)
        self._dispatch.note_output_flags(flags)
        o = _LazyOut(out)
        pid = self._pid_np
        kind = self._kind_np
        # shards whose witness peer needs a snapshot but have no recorded
        # snapshot to strip — they take the regular eviction slow path
        self._wit_snap_fallback: set[int] = set()

        updates: list[pb.Update] = []
        replicates: list[pb.Message] = []
        others: list[pb.Message] = []
        # lanes with anything to process, found VECTORIZED — per-lane
        # Python here was 16 us/lane/step at 100k lanes.  The mask must
        # cover every consumer below: emitted messages and snapshot
        # needs (all eight flag columns), save/apply windows and quiet
        # term/vote/commit changes (_build_update persists a bump even
        # when no message went out), dropped reads (_complete_reads),
        # leader-cache deltas (_leader_edge), and escalation flags;
        # staged proposal fates ride ctx.staged_rows below.
        active = (
            flags.any(1)
            | (o["save_last"] >= o["save_first"])
            | (o["apply_last"] >= o["apply_first"])
            | o["ri_dropped"]
            | o["needs_host"]
            | (o["term"] != self._triple_np[:, 0])
            | (o["vote"] != self._triple_np[:, 1])
            | (o["commit"] != self._triple_np[:, 2])
            | (o["leader"] != self._lead_np)
            | (o["leader_term"] != self._lead_term_np)
        ) & self._occ_np
        cand_ids = set(np.nonzero(active)[0].tolist())
        cand_ids.update(ctx.staged_rows)
        cand_ids.difference_update(ctx.dead)
        # identity check, not membership: a row whose node was removed
        # (and possibly re-admitted) while the step was in flight must
        # not have stale outputs applied to the successor's books
        cand = [(g, nodes[g]) for g in sorted(cand_ids)
                if g in nodes and self.nodes.get(g) is nodes[g]]
        # every processed lane re-stages once next step: multi-window
        # pipelines (apply batches, read books, ring compaction) advance
        # by re-examination, exactly as the full scan did
        for g, _n in cand:
            self._dirty.add(g)
        save_rows = [g for g, n in cand
                     if o["save_last"][g] >= o["save_first"][g]]
        lt_rows = {}
        if save_rows:
            with _capacity.METER.sanctioned("lt_rows"):
                idx = jnp.asarray(np.asarray(save_rows, np.int32))
                lt_rows = dict(zip(save_rows,
                                   np.asarray(self.state.lt[idx])))

        for g, n in cand:
            # 1. proposal fates (origin holds the future's books — on a
            # mesh engine forwarded proposals stage on the leader row)
            fates = ctx.fates.get(g)
            if fates:
                for slot, (entry, origin) in enumerate(fates):
                    if o["prop_accepted"][g, slot]:
                        index = int(o["prop_index"][g, slot])
                        term = int(o["prop_term"][g, slot])
                        n.mirror[index] = _dc_replace(
                            entry, index=index, term=term)
                    else:
                        if entry.is_config_change():
                            origin.pending_config_change.done(
                                entry.key, RequestResultCode.DROPPED)
                        else:
                            origin._rl_release(entry.key)
                            origin.pending_proposals.dropped(entry.key)
            if fates is not None and n._staged_props is fates:
                # serial mode retires before the next staging rebinds
                # the list; pipelined mode's rebind already happened
                n._staged_props = []

            # 2. outgoing messages, gated per class on the flag row
            self._emit_messages(g, n, o, flags[g], pid, kind,
                                replicates, others)

            # 3. persistence batch
            ud = self._build_update(g, n, o, lt_rows.get(g))
            if ud is not None:
                updates.append((n, ud))

        # replicate-before-fsync (engine.go:1332-1343)
        for sender, m in replicates:
            self._send(sender, m)
        if updates:
            # one batched fsync per LogDB (nodes of a shared mesh engine
            # belong to different NodeHosts, each with its own LogDB)
            by_db: dict[int, tuple[object, list]] = {}
            for n, ud in updates:
                by_db.setdefault(id(n.logdb), (n.logdb, []))[1].append(ud)
                if lifecycle.TRACER.enabled:
                    for e in ud.entries_to_save:
                        if e.key:
                            lifecycle.TRACER.stamp(
                                e.key, lifecycle.STAGE_SAVE)
            for db, uds in by_db.values():
                db.save_raft_state(uds, worker_id=0)
        for sender, m in others:
            self._send(sender, m)

        for g, n in cand:
            # a whole-group eviction earlier in THIS loop (mesh engine)
            # already handed the sibling rows to host-resident successor
            # nodes — touching their SMs/books here would race them
            if not self._is_registered(n):
                continue
            n._committed_cache = int(o["commit"][g])
            # 4. ReadIndex results
            self._complete_reads(g, n, o, flags[g], ctx.staged_ri.get(g))
            # 5. apply released entries
            self._apply(g, n, o)
            # 6. leader edges
            self._leader_edge(g, n, int(o["leader"][g]),
                              int(o["leader_term"][g]))
            self._lead_np[g] = int(o["leader"][g])
            self._lead_term_np[g] = int(o["leader_term"][g])
            # 7. escalation
            if o["needs_host"][g]:
                self._evict(n, reason="kernel escalation")
            elif n.shard_id in self._wit_snap_fallback:
                self._evict(n, reason="witness snapshot without record")

    def _emit_messages(self, g, n, o, fl, pid, kind,
                       replicates, others) -> None:
        """Build this row's outgoing messages.  ``fl`` is the row of the
        [G, C] class-activity matrix: a class whose bit is clear is
        never indexed, so its wide output field is never fetched."""
        E = self.kp.msg_entries
        shard = n.shard_id
        # response lanes
        if fl[_F_RESP]:
            for k in range(o["r_type"].shape[1]):
                rt = int(o["r_type"][g, k])
                if rt == 0:
                    continue
                others.append((n, pb.Message(
                    type=pb.MessageType(rt), to=int(o["r_to"][g, k]),
                    from_=n.replica_id, shard_id=shard,
                    term=int(o["r_term"][g, k]),
                    log_index=int(o["r_log_index"][g, k]),
                    reject=bool(o["r_reject"][g, k]),
                    hint=int(o["r_hint"][g, k]),
                    hint_high=int(o["r_hint_high"][g, k]),
                )))
        rep, hb = bool(fl[_F_REP]), bool(fl[_F_HB])
        vote, tnow = bool(fl[_F_VOTE]), bool(fl[_F_TIMEOUT])
        wsnap = bool(fl[_F_WITSNAP])
        if not (rep or hb or vote or tnow or wsnap):
            return
        # per-peer lanes
        for p in range(pid.shape[1]):
            to = int(pid[g, p])
            if to == 0 or to == n.replica_id:
                continue
            to_witness = int(kind[g, p]) == KP.K_WITNESS
            if rep and o["s_rep"][g, p]:
                prev = int(o["s_prev_index"][g, p])
                cnt = int(o["s_n_ent"][g, p])
                ents = []
                for j in range(cnt):
                    idx = prev + 1 + j
                    e = n.mirror.get(idx)
                    term = int(o["s_ent_term"][g, p, j])
                    if e is None:
                        e = pb.Entry(index=idx, term=term)
                    elif e.term != term:
                        e = _dc_replace(e, term=term)
                    if to_witness and not e.is_config_change():
                        # witnesses never see payloads (raft.go:770
                        # makeMetadataEntries); CCs ship in full
                        e = pb.Entry(index=idx, term=term,
                                     type=pb.EntryType.METADATA)
                    ents.append(e)
                replicates.append((n, pb.Message(
                    type=MT.REPLICATE, to=to, from_=n.replica_id,
                    shard_id=shard, term=int(o["term"][g]),
                    log_index=prev, log_term=int(o["s_prev_term"][g, p]),
                    commit=int(o["s_commit"][g, p]),
                    entries=tuple(ents),
                )))
            if wsnap and o["s_wit_snap"][g, p]:
                # witness peer fell behind compaction: answer with the
                # stripped file-less snapshot built from the recorded
                # snapshot (raft.go:713-735) — no stream, no eviction.
                # The record must cover the DEVICE compaction floor: the
                # device paused the peer at psnap = snap_index, and a
                # stale older record would leave a gap the witness can
                # never bridge (re-sent forever) — evict instead.
                ss = n.logdb.get_snapshot(n.shard_id, n.replica_id)
                with _capacity.METER.sanctioned("wit_snap_floor"):
                    floor = int(self.state.snap_index[g])  # wit_snap only
                if ss is not None and not ss.is_empty() \
                        and ss.index >= floor:
                    others.append((n, pb.Message(
                        type=MT.INSTALL_SNAPSHOT, to=to,
                        from_=n.replica_id, shard_id=shard,
                        term=int(o["term"][g]),
                        snapshot=_dc_replace(
                            ss, filepath="", file_size=0, files=(),
                            witness=True, dummy=False),
                    )))
                else:
                    # no record, or one below the device floor — the
                    # regular escalation path recovers the shard
                    self._wit_snap_fallback.add(n.shard_id)
            if hb and o["s_hb"][g, p]:
                others.append((n, pb.Message(
                    type=MT.HEARTBEAT, to=to, from_=n.replica_id,
                    shard_id=shard, term=int(o["term"][g]),
                    commit=int(o["s_hb_commit"][g, p]),
                    hint=int(o["s_hb_low"][g, p]),
                    hint_high=int(o["s_hb_high"][g, p]),
                )))
            sv = int(o["s_vote"][g, p]) if vote else 0
            if sv:
                others.append((n, pb.Message(
                    type=(MT.REQUEST_VOTE if sv == 1
                          else MT.REQUEST_PREVOTE),
                    to=to, from_=n.replica_id, shard_id=shard,
                    term=int(o["s_vote_term"][g, p]),
                    log_index=int(o["s_vote_lindex"][g, p]),
                    log_term=int(o["s_vote_lterm"][g, p]),
                    hint=int(o["s_vote_hint"][g, p]),
                )))
            if tnow and o["s_timeout_now"][g, p]:
                others.append((n, pb.Message(
                    type=MT.TIMEOUT_NOW, to=to, from_=n.replica_id,
                    shard_id=shard, term=int(o["term"][g]))))

    def _build_update(self, g, n, o, lt_row) -> pb.Update | None:
        first, last = int(o["save_first"][g]), int(o["save_last"][g])
        triple = (int(o["term"][g]), int(o["vote"][g]), int(o["commit"][g]))
        entries: list[pb.Entry] = []
        if lt_row is not None and last >= first:
            cap = self.kp.log_cap
            for idx in range(first, last + 1):
                term = int(lt_row[idx & (cap - 1)])
                e = n.mirror.get(idx)
                if e is None or e.term != term:
                    e = (_dc_replace(e, term=term) if e is not None
                         else pb.Entry(index=idx, term=term))
                    n.mirror[idx] = e
                entries.append(e)
        state_changed = tuple(self._triple_np[n.lane]) != triple
        if not entries and not state_changed:
            return None
        self._triple_np[n.lane] = triple
        return pb.Update(
            shard_id=n.shard_id, replica_id=n.replica_id,
            state=pb.State(term=triple[0], vote=triple[1], commit=triple[2]),
            entries_to_save=tuple(entries),
        )

    def _complete_reads(self, g, n, o, fl, staged_ri) -> None:
        """``staged_ri`` is the ReadIndex ctx staged into THIS step (from
        the step ctx — staging for the next step rebinds ``n._staged_ri``
        before a pipelined retire runs)."""
        if fl[_F_RTR]:
            rtr = o["rtr_valid"][g]
            for j in range(rtr.shape[0]):
                if not rtr[j]:
                    continue
                low = int(o["rtr_low"][g, j])
                high = int(o["rtr_high"][g, j])
                index = int(o["rtr_index"][g, j])
                ctx = pb.SystemCtx(low=low, high=high)
                if low in n._local_ri_pending:
                    n._local_ri_pending.pop(low)
                    n.pending_reads.add_ready(ctx, index)
                elif low in n._remote_ri_inflight:
                    # remote read answered: respond to the requester
                    self._send(n, pb.Message(
                        type=MT.READ_INDEX_RESP,
                        to=n._remote_ri_inflight.pop(low),
                        from_=n.replica_id, shard_id=n.shard_id,
                        log_index=index, hint=low, hint_high=high))
        if o["ri_dropped"][g] and staged_ri is not None:
            low = staged_ri.low
            if low in n._local_ri_pending:
                n._local_ri_pending.pop(low)
                n.pending_reads.dropped(staged_ri)
            n._remote_ri_inflight.pop(low, None)
        n.pending_reads.applied(n.sm.get_last_applied())

    def _apply(self, g, n, o) -> None:
        first, last = int(o["apply_first"][g]), int(o["apply_last"][g])
        if last < first:
            return
        entries = []
        for idx in range(first, last + 1):
            e = n.mirror.get(idx)
            if e is None:
                e = pb.Entry(index=idx, term=int(o["term"][g]))
                n.mirror[idx] = e
            entries.append(e)
        for e in entries:
            if e.key:
                n._rl_release(e.key)
        if n.notify_commit:
            for e in entries:
                if e.key:
                    n.pending_proposals.committed(e.key)
        results = n.sm.handle(entries)
        if lifecycle.TRACER.enabled:
            for e in entries:
                if e.key:
                    lifecycle.TRACER.stamp(e.key, lifecycle.STAGE_APPLY)
        cc_applied = False
        for r in results:
            entry = next(e for e in entries if e.index == r.index)
            if entry.is_config_change():
                n._on_config_change_applied(entry, r)
                cc_applied = True
            elif r.key:
                n.pending_proposals.applied(
                    r.key, r.client_id, r.series_id, r.result, r.rejected)
        if cc_applied:
            self.update_lane_membership(n)
        n.applied_since_snapshot += len(results)
        n.pending_reads.applied(n.sm.get_last_applied())
        # auto snapshot + mirror pruning (node.go:694 saveSnapshotRequired)
        if (n.cfg.snapshot_entries > 0
                and n.applied_since_snapshot >= n.cfg.snapshot_entries):
            self._take_lane_snapshot(n, _SnapshotRequest())
        self._prune_mirror(n)

    def _mirror_floor(self, n: KernelNode) -> int:
        """Lowest applied cursor that still needs mirror payloads.  On a
        shared mesh mirror this is the MINIMUM across the shard's
        replicas (a lagging/cut member must still find its entries)."""
        return n.sm.get_last_applied()

    def _prune_mirror(self, n: KernelNode) -> None:
        floor = self._mirror_floor(n) - self.kp.compaction_overhead
        if floor <= 0 or len(n.mirror) <= self.kp.log_cap:
            return
        for idx in [i for i in n.mirror if i < floor]:
            del n.mirror[idx]

    def _take_lane_snapshot(self, n: KernelNode,
                            req: _SnapshotRequest) -> None:
        """Host-side RSM snapshot for a kernel shard (the device compacts
        its ring itself; this makes restart/install possible)."""
        n._take_snapshot(req)

    def _answer_log_query(self, n: KernelNode,
                          lq: tuple[int, int, int]) -> None:
        """QueryRaftLog for a device shard, answered host-side from the
        durable log (every committed entry is persisted before release,
        so the LogDB is authoritative up to the lane's commit cursor)."""
        first, last, max_size = lq
        committed = n._committed_cache
        rs = n.logdb.read_raft_state(n.shard_id, n.replica_id, 0)
        avail_first = rs.first_index if rs is not None else 1
        if first < avail_first:
            n._on_log_query_result(pb.LogQueryResult(
                error=1, first_index=avail_first,
                last_index=committed + 1))
            return
        hi = min(last, committed + 1)
        entries = tuple(n.logdb.iterate_entries(
            n.shard_id, n.replica_id, first, hi, max_size)) if hi > first \
            else ()
        n._on_log_query_result(pb.LogQueryResult(
            error=0, first_index=avail_first, last_index=committed + 1,
            entries=entries))

    def _leader_edge(self, g, n: KernelNode, leader: int, term: int) -> None:
        if (leader, term) == (n._leader_cache, n._leader_term_cache):
            return
        n._leader_cache, n._leader_term_cache = leader, term
        n._last_leader = (leader, term)
        # the node's OWN hub: on a shared mesh engine each replica's
        # listeners live on its attaching NodeHost, not the engine's
        n.events.leader_updated(LeaderInfo(
            shard_id=n.shard_id, replica_id=n.replica_id,
            term=term, leader_id=leader))
        with n.mu:
            awaiting = n._transfer_awaiting
        if awaiting is not None and leader == awaiting[0]:
            n._finish_transfer(RequestResultCode.COMPLETED, leader)

    # -- escalation --------------------------------------------------------

    def _evict(self, n: KernelNode, reason: str,
               carry: list[pb.Message] | None = None) -> None:
        """Move a shard from the kernel to the loopback engine: state is
        already durable via the shared LogDB, so the host rebuilds a
        pycore Node from disk and the shard continues there."""
        if self.remove_shard(n.shard_id) is None:
            return  # already evicted/stopped concurrently
        _LOG.info("shard %d: leaving the kernel (%s)", n.shard_id, reason)
        if self.on_evict is not None:
            self.on_evict(n, carry or [])

    on_evict = None  # set by NodeHost

    def _send(self, n: KernelNode, m: pb.Message) -> None:
        # local delivery between lanes of this engine happens through the
        # sending node's NodeHost dispatch (same path as remote; on a
        # shared mesh engine each node routes via its own host)
        n.send_message(m)


# ---------------------------------------------------------------------------
# staging buffers (numpy first, one device transfer per step)
# ---------------------------------------------------------------------------


_FAMILY_OF_TYPE = {
    int(pb.MessageType.REPLICATE): "rep",
    int(pb.MessageType.HEARTBEAT): "hb",
    int(pb.MessageType.REQUEST_VOTE): "vote",
    int(pb.MessageType.REQUEST_PREVOTE): "vote",
    int(pb.MessageType.TIMEOUT_NOW): "vote",
}
# everything else (responses, NOOP, UNREACHABLE, SNAPSHOT_STATUS) -> "resp"


class _InboxBuilder:
    def __init__(self, G: int, K: int, E: int,
                 mesh_replicas: int | None = None) -> None:
        self.K, self.E = K, E
        # typed slot layout (params.slot_families): a message may only be
        # staged into a slot whose family accepts its type ('any' accepts
        # all) — the kernel compiles family-specialized handlers per slot
        fams = KP.slot_families(K)
        self._slots_for = {}
        for fam in ("rep", "hb", "vote", "resp"):
            self._slots_for[fam] = tuple(
                k for k, f in enumerate(fams) if f in (fam, "any"))
        # slot-exact mode (mesh engines): hub-fallback deliveries must
        # land at the SAME route() slot the mesh exchange would have
        # used, so the merged carried inbox is bit-identical to a fully
        # resident exchange (core/router.py slot_candidates)
        self._mesh_R = mesh_replicas
        self.mtype = np.zeros((G, K), np.int32)
        self.from_ = np.zeros((G, K), np.int32)
        self.term = np.zeros((G, K), np.int32)
        self.log_term = np.zeros((G, K), np.int32)
        self.log_index = np.zeros((G, K), np.int32)
        self.commit = np.zeros((G, K), np.int32)
        self.reject = np.zeros((G, K), bool)
        self.hint = np.zeros((G, K), np.int32)
        self.hint_high = np.zeros((G, K), np.int32)
        self.n_ent = np.zeros((G, K), np.int32)
        self.ent_term = np.zeros((G, K, E), np.int32)
        self.ent_cc = np.zeros((G, K, E), bool)

    def reset(self) -> None:
        for a in (self.mtype, self.from_, self.term, self.log_term,
                  self.log_index, self.commit, self.reject, self.hint,
                  self.hint_high, self.n_ent, self.ent_term, self.ent_cc):
            a.fill(0)

    def add(self, g: int, m: pb.Message, n: KernelNode) -> bool:
        if self._mesh_R is not None:
            R = self._mesh_R
            if m.from_ == n.replica_id or not (1 <= m.from_ <= R):
                # unroutable on the mesh layout: a stray delivery, not a
                # full inbox — swallow it (True = no requeue) like the
                # pre-round-17 hub drop did
                return True
            cands = _router.slot_candidates(
                n.replica_id, m.from_, R, int(m.type))
        else:
            cands = self._slots_for[_FAMILY_OF_TYPE.get(int(m.type), "resp")]
        k = -1
        for cand in cands:
            if self.mtype[g, cand] == 0:
                k = cand
                break
        if k < 0:
            return False  # family full this step; host requeues the message
        self.mtype[g, k] = int(m.type)
        self.from_[g, k] = m.from_
        self.term[g, k] = m.term
        self.log_term[g, k] = m.log_term
        self.log_index[g, k] = m.log_index
        self.commit[g, k] = m.commit
        self.reject[g, k] = m.reject
        self.hint[g, k] = m.hint
        self.hint_high[g, k] = m.hint_high
        ents = m.entries[:self.E]
        self.n_ent[g, k] = len(ents)
        for j, e in enumerate(ents):
            self.ent_term[g, k, j] = e.term
            self.ent_cc[g, k, j] = e.is_config_change()
            # stage payloads; the kernel decides acceptance, and content
            # at-or-below commit is invariant so overwrites are safe
            n.mirror[e.index] = e
        return True

    def to_device(self) -> Inbox:
        with _capacity.METER.sanctioned("inbox_up"):
            return Inbox(
                mtype=jnp.asarray(self.mtype),
                from_=jnp.asarray(self.from_),
                term=jnp.asarray(self.term),
                log_term=jnp.asarray(self.log_term),
                log_index=jnp.asarray(self.log_index),
                commit=jnp.asarray(self.commit),
                reject=jnp.asarray(self.reject),
                hint=jnp.asarray(self.hint),
                hint_high=jnp.asarray(self.hint_high),
                n_ent=jnp.asarray(self.n_ent),
                ent_term=jnp.asarray(self.ent_term),
                ent_cc=jnp.asarray(self.ent_cc),
            )


class _InputBuilder:
    def __init__(self, G: int, B: int) -> None:
        self.B = B
        self.prop_valid = np.zeros((G, B), bool)
        self.prop_cc = np.zeros((G, B), bool)
        self.ri_valid = np.zeros((G,), bool)
        self.ri_low = np.zeros((G,), np.int32)
        self.ri_high = np.zeros((G,), np.int32)
        self.transfer_to = np.zeros((G,), np.int32)
        self._tick = np.zeros((G,), bool)
        self._applied = np.zeros((G,), np.int32)

    def reset(self) -> None:
        for a in (self.prop_valid, self.prop_cc, self.ri_valid, self.ri_low,
                  self.ri_high, self.transfer_to, self._tick, self._applied):
            a.fill(0)

    def prop(self, g: int, slot: int, is_cc: bool) -> None:
        self.prop_valid[g, slot] = True
        self.prop_cc[g, slot] = is_cc

    def read(self, g: int, ctx: pb.SystemCtx) -> None:
        self.ri_valid[g] = True
        self.ri_low[g] = ctx.low & 0x7FFFFFFF
        self.ri_high[g] = ctx.high & 0x7FFFFFFF

    def transfer(self, g: int, target: int) -> None:
        self.transfer_to[g] = target

    def tick(self, g: int) -> None:
        self._tick[g] = True

    def applied(self, g: int, v: int) -> None:
        self._applied[g] = v

    def to_device(self) -> StepInput:
        with _capacity.METER.sanctioned("input_up"):
            return StepInput(
                prop_valid=jnp.asarray(self.prop_valid),
                prop_cc=jnp.asarray(self.prop_cc),
                ri_valid=jnp.asarray(self.ri_valid),
                ri_low=jnp.asarray(self.ri_low),
                ri_high=jnp.asarray(self.ri_high),
                transfer_to=jnp.asarray(self.transfer_to),
                tick=jnp.asarray(self._tick),
                quiesced=jnp.zeros_like(self._tick),
                applied=jnp.asarray(self._applied),
            )
