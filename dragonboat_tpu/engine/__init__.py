"""engine — execution engines that advance raft shards.

The loopback engine (NodeHost's thread stepping host-Python ``Node``s) is
the reference-shaped path (engine.go worker pools collapsed to one
executor).  ``KernelEngine`` is the TPU-native replacement: every
device-resident shard lives as one lane of a batched ``[G]`` kernel state,
one jitted step advances all of them, and the host marshals client
requests, transport messages, persistence and RSM applies around it
(engine.go:1107-1364 re-expressed as a data-parallel device program).
"""

from dragonboat_tpu.engine.kernel_engine import KernelEngine

__all__ = ["KernelEngine"]
