"""ApplyPool — dedicated RSM-apply workers for host-resident shards.

The reference isolates user state-machine latency from the raft step
path with separate apply workers (``engine.go:1153-1204`` applyWorkerMain
/ commitWorkerMain): a step worker persists and hands committed entries
off; a slow ``Update()`` can only ever stall its own shard, never the
stepping of the other shards in its partition.

This pool implements that contract with a ready-queue of shard keys and
one FIFO of closures per shard: a worker claims a shard exclusively,
drains the closures queued so far, and re-queues the shard if more
arrived while it ran.  Per-shard order is preserved; a shard whose SM
blocks occupies exactly one worker.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable

from dragonboat_tpu import lifecycle


class ApplyPool:
    def __init__(self, num_workers: int = 4,
                 on_work_done: Callable[[], None] | None = None,
                 name: str = "apply") -> None:
        self._cv = threading.Condition()
        self._queues: dict[object, deque] = {}   # guarded-by: _cv
        self._ready: deque = deque()             # guarded-by: _cv — keys with work, not being run
        self._running: set = set()               # guarded-by: _cv
        self._stopped = False                    # guarded-by: _cv
        self._on_work_done = on_work_done
        self._threads = []                       # guarded-by: <init-only>
        for i in range(max(1, num_workers)):
            t = threading.Thread(target=self._worker_main,
                                 name=f"{name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def submit(self, key, fn: Callable[[], None],
               trace_keys: tuple = ()) -> None:
        """Enqueue ``fn`` on ``key``'s serial lane.  ``trace_keys`` are
        sampled proposal keys riding in this closure: the worker stamps
        their lifecycle spans when the closure actually starts, so the
        apply_queue->apply delta measures real pool dwell."""
        with self._cv:
            if self._stopped:
                return
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
            q.append((fn, trace_keys))
            if key not in self._running and key not in self._ready:
                self._ready.append(key)
                self._cv.notify()

    def flush(self, key, timeout: float = 10.0) -> bool:
        """Block until ``key`` has no queued or running work (shard stop:
        the SM must not be closed under a still-running apply)."""
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        with self._cv:
            return self._cv.wait_for(
                lambda: key not in self._running
                and not self._queues.get(key),
                timeout=deadline)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._queues.clear()
            self._ready.clear()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)

    def _worker_main(self) -> None:
        while True:
            with self._cv:
                while not self._ready and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                key = self._ready.popleft()
                q = self._queues.get(key)
                if not q:
                    continue
                batch, self._queues[key] = q, deque()
                self._running.add(key)
            try:
                for fn, trace_keys in batch:
                    for tk in trace_keys:
                        lifecycle.TRACER.stamp(tk, lifecycle.STAGE_APPLY)
                    try:
                        fn()
                    except Exception:
                        from dragonboat_tpu.logger import get_logger

                        get_logger("engine").exception(
                            "apply work for %r failed", key)
            finally:
                with self._cv:
                    self._running.discard(key)
                    if self._queues.get(key):
                        self._ready.append(key)
                        self._cv.notify()
                    else:
                        # retired/idle keys must not leak a dict slot
                        # per shard forever (100k-group scale)
                        self._queues.pop(key, None)
                    self._cv.notify_all()  # wake flush() waiters
            if self._on_work_done is not None:
                self._on_work_done()
