"""Unified engine dispatch: ONE step loop, two jit backends.

PR 6 gave the single-device engine donation + depth-1 software
pipelining; the mesh engine's bespoke ``_kernel_call`` never caught up
(no donation, no pipelined entry, its own telemetry wiring) — the exact
engine-layer drift the engine-unity lint pass (analysis/engine_unity.py,
EU001–EU006) now makes a failure.  This module is the refactor that
makes the repo clean: ``KernelEngine.step_all`` remains the ONLY step
loop, and the only thing a backend contributes is a ``dispatch()`` —
serial jit (core/kernel.py ``step``/``step_donated``) or the
``parallel/ici.py`` shard_map serving entries — each exposed as a
donated + non-donated pair behind CompileTracker telemetry, so the
pipelined retire-before-dispatch protocol and the masked output fetch
work identically on both paths.

The module-level tuples/dicts below are the MACHINE-READ contract the
engine-unity pass enforces (pure literals, parsed with
``ast.literal_eval`` — like kstate's CONTRACTS/DONATION tables):

- ``STEP_LOOP_METHODS``: step-loop internals only ``STEP_LOOP_OWNER``
  may define — a subclass override is a second step loop (EU001);
- ``DISPATCH_SEAMS``: the sanctioned subclass seams (addressing,
  membership, escalation, message emission, and ``_make_dispatch``);
- ``ENGINE_FEATURE_KNOBS`` / ``ENGINE_FEATURE_CALLS``: dispatch
  features that must be reachable from ``step_all`` on every engine
  path (EU002/EU004);
- ``DISPATCH_ENTRIES``: every jit entry a dispatch backend may call —
  donated ones must carry a kstate.DONATION declaration (EU003,
  composing with KC008/PS004), non-donated ones a waiver naming why.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dragonboat_tpu import capacity as _capacity
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kernel import (
    FLAG_CLASSES,
    step as kernel_step,
    step_donated as kernel_step_donated,
)
from dragonboat_tpu.core.kstate import empty_inbox
from dragonboat_tpu.parallel.ici import (
    IciCluster,
    jit_serve_step,
    jit_serve_step_donated,
)

#: the one class allowed to define step-loop internals
STEP_LOOP_OWNER = "KernelEngine"

#: step-loop internals: defining any of these in a subclass of the owner
#: is a second step-loop implementation (EU001)
STEP_LOOP_METHODS = (
    "step_all",
    "_flush_injections",
    "_stage_lane",
    "_stage_props",
    "_process_outputs",
    "_kernel_call",
    "_capacity_entries",
    "_device_pending",
    "_fleet_inbox_from",
    "_capacity_trees",
    "_capacity_model_classes",
    "_make_health_digest",
    "_make_invariant_digest",
)

#: sanctioned subclass seams: addressing, membership, escalation,
#: host-side message emission, and the dispatch-backend factory
DISPATCH_SEAMS = (
    "_make_dispatch",
    "_emit_messages",
    "_prop_target",
    "_mirror_floor",
    "_is_registered",
    "_evict",
    "add_shard",
    "remove_shard",
    "update_lane_membership",
)

#: ExpertConfig-fed engine attributes gating dispatch features; every
#: one must be read on a path reachable from step_all in EVERY concrete
#: engine (EU002 flags per-path drift)
ENGINE_FEATURE_KNOBS = (
    "pipeline_depth",
    "fleet_stats_every",
    "health_top_k",
    "invariant_probe",
)

#: feature calls (not attributes) that must stay reachable from the
#: step loop on every path — the masked output fetch is gated on the
#: [G, C] activity matrix this produces
ENGINE_FEATURE_CALLS = ("output_row_flags",)

#: every jit entry a dispatch backend may call.  ``donated`` entries
#: must be kstate.DONATION-declared (EU003 cross-checks via KC008);
#: non-donated entries carry a waiver naming why donation is out.
DISPATCH_ENTRIES = {
    "step": {
        "module": "dragonboat_tpu/core/kernel.py",
        "function": "step",
        "donated": False,
        "waiver": "depth-0 serial oracle: the differential reference "
                  "entry must leave its inputs readable",
    },
    "step_donated": {
        "module": "dragonboat_tpu/core/kernel.py",
        "function": "step_donated",
        "donated": True,
        "waiver": "",
    },
    "serve_step": {
        "module": "dragonboat_tpu/parallel/ici.py",
        "function": "jit_serve_step",
        "donated": False,
        "waiver": "depth-0 mesh oracle: the differential reference "
                  "entry must leave its inputs readable",
    },
    "serve_step_donated": {
        "module": "dragonboat_tpu/parallel/ici.py",
        "function": "jit_serve_step_donated",
        "donated": True,
        "waiver": "",
    },
}

#: every sanctioned device->host SYNC site in the engine layer, keyed by
#: the host-side qualname whose body may force a device value
#: (``int()`` / ``.item()`` / ``np.asarray`` / ``block_until_ready``).
#: The transfer pass (analysis/transfer.py TB005 — the engine-scope
#: sharpening of PS006) fails any other engine-layer sync; the runtime
#: leg counts each under ``tag`` via capacity.METER.  Declaring a site
#: here is a REVIEWED claim that the sync is off the per-step critical
#: path or deliberately masked/lazy.
SYNC_POINTS = {
    "_LazyOut.__getitem__": {
        "tag": "lazy_out",
        "why": "memoized per-field StepOutput fetch — the masked-fetch "
               "path that replaced the eager 42-field sweep",
    },
    "KernelEngine._process_outputs": {
        "tag": "output_flags",
        "why": "the [G, 8] activity matrix gating the masked fetch, "
               "plus the save-window lt rows for persisted lanes",
    },
    "KernelEngine._emit_messages": {
        "tag": "wit_snap_floor",
        "why": "witness-snapshot floor probe (snap_index scalar) on the "
               "rare wit_snap retire path only",
    },
}

#: the machine-read transfer contract: every value crossing the
#: device<->host boundary through the dispatch seam, per jit entry
#: (analysis/transfer.py sizes each row in closed form from the
#: CONTRACTS grammar and gates the per-step totals against
#: analysis/transfer_budget.json).  Row schema:
#:   value    contract class name or inline contract string
#:   param    entry parameter the upload binds (classification cross-check)
#:   site     host qualname performing the crossing
#:   tag      capacity.METER tag the site counts under
#:   per_step crossing happens on EVERY step of this entry's profile
#:   masked   download is lane/field-masked (the _LazyOut discipline)
#:   cached   upload is memoized until invalidated (not per-step)
#: ``_control`` rows are step-loop control-plane crossings (admissions,
#: membership, telemetry) that belong to no single entry.
TRANSFER_LEDGER = {
    "step": {
        "resident": ("ShardState",),
        "up": (
            {"value": "Inbox", "param": "inbox",
             "site": "_InboxBuilder.to_device", "tag": "inbox_up",
             "per_step": True},
            {"value": "StepInput", "param": "inp",
             "site": "_InputBuilder.to_device", "tag": "input_up",
             "per_step": True},
        ),
        "down": (
            {"value": "[G, 8] bool",
             "site": "KernelEngine._process_outputs",
             "tag": "output_flags", "per_step": True},
            {"value": "StepOutput", "site": "_LazyOut.__getitem__",
             "tag": "lazy_out", "per_step": False, "masked": True},
            {"value": "[G, CAP] i32",
             "site": "KernelEngine._process_outputs", "tag": "lt_rows",
             "per_step": False, "masked": True},
        ),
    },
    "step_donated": {
        "resident": ("ShardState",),
        "up": (
            {"value": "Inbox", "param": "inbox",
             "site": "_InboxBuilder.to_device", "tag": "inbox_up",
             "per_step": True},
            {"value": "StepInput", "param": "inp",
             "site": "_InputBuilder.to_device", "tag": "input_up",
             "per_step": True},
        ),
        "down": (
            {"value": "[G, 8] bool",
             "site": "KernelEngine._process_outputs",
             "tag": "output_flags", "per_step": True},
            {"value": "StepOutput", "site": "_LazyOut.__getitem__",
             "tag": "lazy_out", "per_step": False, "masked": True},
            {"value": "[G, CAP] i32",
             "site": "KernelEngine._process_outputs", "tag": "lt_rows",
             "per_step": False, "masked": True},
        ),
    },
    "serve_step": {
        "resident": ("ShardState", "Inbox"),
        "up": (
            {"value": "StepInput", "param": "inp",
             "site": "_InputBuilder.to_device", "tag": "input_up",
             "per_step": True},
            {"value": "[G, P] bool", "param": "cut",
             "site": "MeshDispatch.dispatch", "tag": "cut_up",
             "per_step": False, "cached": True},
            {"value": "Inbox", "site": "_InboxBuilder.to_device",
             "tag": "inbox_up", "per_step": False},
        ),
        "down": (
            {"value": "[G, 8] bool",
             "site": "KernelEngine._process_outputs",
             "tag": "output_flags", "per_step": True},
            {"value": "StepOutput", "site": "_LazyOut.__getitem__",
             "tag": "lazy_out", "per_step": False, "masked": True},
            {"value": "[G, CAP] i32",
             "site": "KernelEngine._process_outputs", "tag": "lt_rows",
             "per_step": False, "masked": True},
        ),
    },
    "serve_step_donated": {
        "resident": ("ShardState", "Inbox"),
        "up": (
            {"value": "StepInput", "param": "inp",
             "site": "_InputBuilder.to_device", "tag": "input_up",
             "per_step": True},
            {"value": "[G, P] bool", "param": "cut",
             "site": "MeshDispatch.dispatch", "tag": "cut_up",
             "per_step": False, "cached": True},
            {"value": "Inbox", "site": "_InboxBuilder.to_device",
             "tag": "inbox_up", "per_step": False},
        ),
        "down": (
            {"value": "[G, 8] bool",
             "site": "KernelEngine._process_outputs",
             "tag": "output_flags", "per_step": True},
            {"value": "StepOutput", "site": "_LazyOut.__getitem__",
             "tag": "lazy_out", "per_step": False, "masked": True},
            {"value": "[G, CAP] i32",
             "site": "KernelEngine._process_outputs", "tag": "lt_rows",
             "per_step": False, "masked": True},
        ),
    },
    "fleet_stats": {
        "resident": ("ShardState",),
        "up": (
            {"value": "[G, K] i32", "param": "inbox_from",
             "site": "KernelEngine._collect_fleet_stats",
             "tag": "fleet_down", "per_step": False},
        ),
        "down": (
            {"value": "FleetStats",
             "site": "KernelEngine._collect_fleet_stats",
             "tag": "fleet_down", "per_step": False},
        ),
    },
    "fleet_health": {
        "resident": ("ShardState", "HealthDigest"),
        "up": (
            {"value": "[G, K] i32", "param": "inbox_from",
             "site": "KernelEngine._collect_health",
             "tag": "health_down", "per_step": False},
        ),
        "down": (
            {"value": "HealthReport",
             "site": "KernelEngine._collect_health",
             "tag": "health_down", "per_step": False},
        ),
    },
    "check_invariants": {
        "resident": ("ShardState", "InvariantDigest"),
        "up": (
            {"value": "[G] i32",
             "site": "KernelEngine._collect_invariants",
             "tag": "invariants_down", "per_step": False},
        ),
        "down": (
            {"value": "InvariantReport",
             "site": "KernelEngine._collect_invariants",
             "tag": "invariants_down", "per_step": False},
        ),
    },
    "_control": (
        {"value": "ShardState", "dir": "up",
         "site": "KernelEngine._flush_injections", "tag": "inject_up",
         "per_step": False},
        {"value": "[G, P] i32", "dir": "up",
         "site": "KernelEngine.update_lane_membership",
         "tag": "membership_up", "per_step": False},
        {"value": "[G, P] i32", "dir": "up",
         "site": "MeshEngine.update_lane_membership",
         "tag": "membership_up", "per_step": False},
        {"value": "ShardRow", "dir": "down",
         "site": "KernelEngine.health_row", "tag": "health_row",
         "per_step": False},
        {"value": "[G] i32", "dir": "down",
         "site": "KernelEngine._emit_messages", "tag": "wit_snap_floor",
         "per_step": False},
    ),
}


class SerialDispatch:
    """Single-device backend: inbox re-staged from host every step."""

    def __init__(self, kp: KP.KernelParams,
                 step_fn=None, donated_fn=None) -> None:
        self.kp = kp
        # per-instance telemetry wrappers (own counters): a first
        # compile at THIS engine's geometry is never mistaken for a
        # retrace of another engine sharing the jitted function.
        # step_fn/donated_fn let the engine bind ITS module globals
        # (chaos tests swap in mutated kernels there)
        self.entries = {
            "step": _capacity.TRACKER.wrap(
                "step", step_fn if step_fn is not None else kernel_step),
            "step_donated": _capacity.TRACKER.wrap(
                "step_donated",
                donated_fn if donated_fn is not None
                else kernel_step_donated),
        }

    def dispatch(self, state, inbox, inp, donate: bool):
        """One jitted step.  ``donate=True`` routes through the donating
        entry (core/kernel.py ``step_donated``): XLA reuses the
        state/inbox/input buffers, so after this call the host must not
        read them again — step_all's retire-before-dispatch order
        upholds that."""
        entry = self.entries["step_donated" if donate else "step"]
        return entry(self.kp, state, inbox.to_device(), inp.to_device())

    def pending(self) -> bool:
        """No device-resident inbox: nothing carries between steps."""
        return False

    def note_output_flags(self, flags) -> None:
        """No carried inbox, so retired activity flags carry no drain
        information here; MeshDispatch derives pending() from them."""

    def inbox_from(self, inbox_buf):
        """[G, K] sender ids for the inbox-occupancy histogram — the
        host-staged builder is the inbox here."""
        return inbox_buf.from_

    def shard(self, tree):
        """Single device: placement is a no-op."""
        return tree

    def resident_trees(self) -> tuple:
        return ()

    def resident_classes(self) -> tuple:
        return ()


#: FLAG_CLASSES columns that carry inter-replica messages — the classes
#: whose routed traffic keeps the mesh draining (need_snapshot/wit_snap/
#: rtr are host-escalation signals, not inbox content)
_MSG_FLAG_COLS = [FLAG_CLASSES.index(c)
                  for c in ("resp", "rep", "hb", "vote", "timeout_now")]


class MeshDispatch:
    """shard_map backend over a ``Mesh(('g','r'))``: messages ride the
    mesh inside the step (parallel/ici.py), the inbox is device-resident
    between steps, and a per-link cut mask decides which links the mesh
    serves — traffic for cut links (and off-mesh peers) rides the host
    hub and is merged back into the carried inbox at its route() slot."""

    def __init__(self, cluster: IciCluster) -> None:
        self.cluster = cluster
        total = cluster.total_rows
        # device-resident inbox carried between steps (messages ride
        # the mesh, not the host queues)
        self.box = cluster.shard(empty_inbox(cluster.kp, total))
        # drain-pending, derived host-side from the [G, C] activity
        # flags the step loop already fetches every step — the round-16
        # per-step pending-scalar download is gone
        self._pending_msgs = False
        # per-link cut mask [rows, num_peers]: cut[row, p] severs the
        # mesh link between the row and its group peer rid p+1 (mesh
        # addressing pins peer slot p to rid p+1).  Device copy cached
        # until the mask changes.
        self.cut = np.zeros((total, cluster.kp.num_peers), bool)
        self._cut_dev = None
        self.entries = {
            "serve_step": _capacity.TRACKER.wrap(
                "serve_step", jit_serve_step),
            "serve_step_donated": _capacity.TRACKER.wrap(
                "serve_step_donated", jit_serve_step_donated),
        }

    def dispatch(self, state, inbox, inp, donate: bool):
        """Advance the mesh: host-staged inputs, device-routed messages.
        Kernel-family traffic between mesh rows rides the exchange
        inside the step; the host inbox builder is consulted ONLY for
        hub-fallback deliveries (cut links, off-mesh senders), staged
        slot-exact by _InboxBuilder and merged into the carried inbox
        before the entry runs.  ``donate=True`` hands state, the carried
        inbox and the staged input to XLA (kstate.DONATION
        ``serve_step_donated``); the cached cut mask is never donated."""
        cl = self.cluster
        if inbox is not None and inbox.mtype.any():
            staged_box = cl.shard(inbox.to_device())
            if self.box.ent_val is not None and staged_box.ent_val is None:
                staged_box = staged_box._replace(
                    ent_val=jnp.zeros_like(self.box.ent_val))
            live = staged_box.mtype != 0
            self.box = jax.tree.map(
                lambda s, b: jnp.where(
                    live.reshape(live.shape + (1,) * (s.ndim - 2)), s, b),
                staged_box, self.box)
        staged = cl.shard(inp.to_device())
        if self._cut_dev is None:
            with _capacity.METER.sanctioned("cut_up"):
                self._cut_dev = cl.shard(jnp.asarray(self.cut))
        entry = self.entries["serve_step_donated" if donate
                             else "serve_step"]
        state, box, out = entry(
            cl.kp, cl, state, self.box, staged, self._cut_dev)
        self.box = box
        return state, out

    def pending(self) -> bool:
        return self._pending_msgs

    def note_output_flags(self, flags) -> None:
        """Derive drain-pending from the retired step's [G, C] activity
        flags (already host-side — no extra crossing): any messaging
        class set means the exchange routed traffic into the carried
        inbox (or the hub is about to carry it), so the next step has
        work.  Conservative under cut links — flags are computed from
        the unmasked output, so a fully-cut row costs at most one idle
        step — and never an undercount: the carried inbox only ever
        holds routed copies of flagged output lanes."""
        self._pending_msgs = bool(flags[:, _MSG_FLAG_COLS].any())

    def inbox_from(self, inbox_buf):
        # the mesh inbox is device-resident between steps; no host copy
        return self.box.from_

    def shard(self, tree):
        """Place a [G]-leading pytree onto the mesh (digests and the
        like shard along G exactly like the state they derive from)."""
        return self.cluster.shard(tree)

    def set_cut(self, lane: int, cut: bool) -> None:
        """Flip one row's WHOLE partition mask (every link of the row)
        and invalidate the cached device copy (next dispatch re-stages
        it).  This is the chaos PartitionNode surface: the row neither
        sends nor receives on the mesh."""
        self.cut[lane, :] = cut
        self._cut_dev = None

    def set_link_cut(self, lane: int, peer_rid: int, cut: bool) -> None:
        """Flip ONE directed half-link: row ``lane`` stops exchanging
        with group peer rid ``peer_rid`` over the mesh.  Callers must
        cut links symmetrically (both endpoints) — hub fallback relies
        on the peer's sender-side mask to emit its half over the host."""
        self.cut[lane, peer_rid - 1] = cut
        self._cut_dev = None

    def resident_trees(self) -> tuple:
        # the carried inbox is device-resident between steps here
        return (self.box,)

    def resident_classes(self) -> tuple:
        return ("Inbox",)
