"""MeshEngine — raft groups whose replicas span a multi-chip device mesh.

The reference scales by running one NodeHost per machine and moving every
inter-replica message through its TCP transport (transport.go:86-101,
engine.go:1230-1364).  Here the replicas of a mesh-resident shard are rows
of ONE sharded kernel state over a ``Mesh(('g','r'))``: replica ``i`` of a
group lives on a device along axis ``'r'``, and message exchange is the
``all_gather``+route inside the jitted step (parallel/ici.py) — the
transport seam collapses into an ICI collective while the host keeps the
same serving duties the single-device KernelEngine has:

  - client proposals / ReadIndex staged into StepInput lanes (with
    follower-host proposals forwarded in-engine to the leader row — the
    reference forwards MsgProp through the raft core);
  - ONE batched ``save_raft_state`` fsync per LogDB per step;
  - snapshots, log queries, eviction to host engines as the slow path.

Deployment note: in this process every attached NodeHost drives its own
replicas and ONE shared engine advances the mesh — the in-process form of
a jax multi-host SPMD program where each host owns a slice of the global
mesh.  Payload bytes live in a per-shard mirror shared by the replicas
(the in-process form of payload distribution; the device ring carries
terms, and ``KernelParams.inline_payloads`` carries values for the
device-native RSM).  Partition chaos (monkey.go:170) is a device-side
mask: a cut row neither sends nor receives on the mesh.

Escalation is whole-group: all state is durable through each replica's
LogDB, so on ``needs_host`` (or InstallSnapshot, or a membership the mesh
cannot address) every member is rebuilt as a host-resident pycore Node on
its own NodeHost and the group continues over the regular transport.
"""

from __future__ import annotations

import threading

import jax
import numpy as np
from jax.sharding import Mesh

from dragonboat_tpu import capacity as _capacity
from dragonboat_tpu import fabric as _fabric
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import MeshSpec
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kstate import init_state
from dragonboat_tpu.engine.kernel_engine import (
    KernelEngine,
    KernelNode,
    _F_WITSNAP,
    _KERNEL_MTYPES,
    _LaneInit,
)
from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.parallel.ici import IciCluster

_LOG = get_logger("mesh_engine")

MT = pb.MessageType


class MeshEngine(KernelEngine):
    """A KernelEngine whose rows span a device mesh.

    Row layout matches parallel/ici.py block-major addressing: row
    ``((ig * R) + ir) * n_local + n`` is replica ``ir + 1`` of group lane
    ``ig * n_local + n``; a flat ``P(('g','r'))`` sharding then gives
    device ``(ig, ir)`` the rows of its replica slot."""

    def __init__(self, kp: KP.KernelParams, spec: MeshSpec,
                 events=None, fleet_stats_every: int = 10,
                 pipeline_depth: int = 0,
                 health_top_k: int = 8,
                 health_thresholds=None,
                 invariant_probe: bool = True,
                 capacity_watermark_pct: float = 10.0,
                 capacity_budget_bytes: int = 0) -> None:
        devs = jax.devices()
        need = spec.g_size * spec.replicas
        if len(devs) < need:
            raise RuntimeError(
                f"mesh '{spec.name}' needs {need} devices, have {len(devs)}")
        mesh = Mesh(
            np.array(devs[:need]).reshape(spec.g_size, spec.replicas),
            ("g", "r"))
        self.spec = spec
        self.cluster = IciCluster(
            kp=kp, mesh=mesh, replicas=spec.replicas,
            n_local=spec.n_local, num_groups=spec.g_size * spec.n_local)
        total = self.cluster.total_rows
        # read by KernelEngine.__init__ below: hub-fallback deliveries
        # stage slot-exact against route()'s layout (_InboxBuilder)
        self._slot_exact_replicas = spec.replicas
        super().__init__(kp, total, send_message=None, events=events,
                         fleet_stats_every=fleet_stats_every,
                         pipeline_depth=pipeline_depth,
                         health_top_k=health_top_k,
                         health_thresholds=health_thresholds,
                         invariant_probe=invariant_probe,
                         capacity_watermark_pct=capacity_watermark_pct,
                         capacity_budget_bytes=capacity_budget_bytes)
        # replica ids are fixed by the mesh addressing (route() targets
        # rid 1..R); rows keep them even while ABSENT
        rids = np.empty((total,), np.int32)
        for ig in range(spec.g_size):
            for ir in range(spec.replicas):
                lo = (ig * spec.replicas + ir) * spec.n_local
                rids[lo:lo + spec.n_local] = ir + 1
        self.state = self.cluster.shard(init_state(
            kp, total, replica_id=rids,
            peer_ids=np.zeros((total, kp.num_peers), np.int32)))
        # group-lane bookkeeping
        self._lane_of: dict[int, int] = {}            # shard_id -> lane
        # newest membership ccid written to each group's shared peer
        # books (guards against lagging-member rollback)
        self._books_ccid: dict[int, int] = {}
        self._members: dict[int, dict[int, KernelNode]] = {}  # sid -> rid -> n
        self._mirrors: dict[int, dict[int, pb.Entry]] = {}    # sid -> mirror
        self._free_lanes = list(range(self.cluster.num_groups - 1, -1, -1))
        self._free = []   # base's row free-list is unused (rows are fixed)
        self._refs = 0    # attached NodeHosts (registry lifecycle)

    # -- row addressing ----------------------------------------------------

    def _row(self, lane: int, replica_id: int) -> int:
        R, n_local = self.spec.replicas, self.spec.n_local
        ig, n = divmod(lane, n_local)
        return (ig * R + (replica_id - 1)) * n_local + n

    # -- lane lifecycle ----------------------------------------------------

    def add_shard(self, node: KernelNode, init: _LaneInit) -> None:
        """Place one REPLICA into its mesh row.  The first member of a
        shard allocates the group lane; later members (possibly attached
        by other NodeHosts, possibly after a restart) join it."""
        rids = [rid for rid, _ in init.peers]
        if any(not (1 <= r <= self.spec.replicas) for r in rids) or not (
                1 <= node.replica_id <= self.spec.replicas):
            raise ValueError(
                f"mesh-resident shard {node.shard_id}: replica ids {rids} "
                f"outside mesh addressing 1..{self.spec.replicas}")
        if any(kind == KP.K_WITNESS for _, kind in init.peers):
            # admission-time twin of the update_lane_membership guard: a
            # restart rebuilds init.peers from the durable membership, and
            # a witness member must keep the group on the host engines
            # (its mesh row would be ABSENT — traffic to it vanishes)
            raise ValueError(
                f"mesh-resident shard {node.shard_id}: witness members "
                f"are host-engine only")
        with self.mu:
            lane = self._lane_of.get(node.shard_id)
            if lane is None:
                if not self._free_lanes:
                    raise RuntimeError("mesh engine is at capacity")
                lane = self._free_lanes.pop()
                self._lane_of[node.shard_id] = lane
                self._members[node.shard_id] = {}
                self._mirrors[node.shard_id] = {}
            members = self._members[node.shard_id]
            if node.replica_id in members:
                raise RuntimeError(
                    f"replica {node.replica_id} of shard {node.shard_id} "
                    f"already mesh-resident")
            row = self._row(lane, node.replica_id)
            node.lane = row
            node.engine = self
            node.mirror = self._mirrors[node.shard_id]   # shared payloads
            members[node.replica_id] = node
            self.nodes[row] = node
            self.by_shard[(node.shard_id, node.replica_id)] = node
            self._inject(row, node, init)
            self._note_link_classes(node)

    def remove_replica(self, node: KernelNode) -> KernelNode | None:
        """Detach one replica (stop_replica / NodeHost.close); the group
        lane lives on for the remaining members."""
        with self.mu:
            if self.by_shard.pop((node.shard_id, node.replica_id),
                                 None) is None:
                return None
            addr = self._link_class_book(node).get(node.replica_id)
            if addr:
                _fabric.METER.drop_link_classes(addr)
            members = self._members.get(node.shard_id, {})
            members.pop(node.replica_id, None)
            self.nodes.pop(node.lane, None)
            self._removed_nodes.append(node)
            self._clear_lane(node.lane)
            self._dispatch.set_cut(node.lane, False)
            if not members:
                lane = self._lane_of.pop(node.shard_id, None)
                self._members.pop(node.shard_id, None)
                self._mirrors.pop(node.shard_id, None)
                self._books_ccid.pop(node.shard_id, None)
                if lane is not None:
                    self._free_lanes.append(lane)
        return node

    def remove_shard(self, shard_id: int) -> KernelNode | None:
        raise NotImplementedError(
            "mesh engine removes per-replica: use remove_replica(node)")

    def _is_registered(self, n: KernelNode) -> bool:
        # identity, for the same reason as the base engine: a deferred
        # retire must not mistake a re-admitted replica for this node
        return self.by_shard.get((n.shard_id, n.replica_id)) is n

    def _mirror_floor(self, n: KernelNode) -> int:
        members = self._members.get(n.shard_id, {}).values()
        return min((m.sm.get_last_applied() for m in members),
                   default=n.sm.get_last_applied())

    # -- fabric link classes ----------------------------------------------

    @staticmethod
    def _link_class_book(node: KernelNode) -> dict:
        """rid -> raft address from the node's own durable membership —
        the same book update_lane_membership reads."""
        m = node.sm.get_membership()
        return {**m.addresses, **m.non_votings, **m.witnesses}

    def _note_link_classes(self, node: KernelNode) -> None:
        """Refresh the fabric meter's carrier class for every co-
        resident link of ``node`` from the live cut mask (resident =
        mesh-carried, hub = cut/partitioned), both directions.  Links
        to absent or off-mesh peers stay unregistered: they are hub
        links by construction and the meter already counts their
        traffic.  Caller holds self.mu; the meter takes only its own
        lock."""
        book = self._link_class_book(node)
        me = book.get(node.replica_id)
        if not me:
            return
        for rid, peer in self._members.get(node.shard_id, {}).items():
            if rid == node.replica_id:
                continue
            them = self._link_class_book(peer).get(rid) or book.get(rid)
            if not them:
                continue
            cls = (_fabric.LINK_CLASS_HUB
                   if bool(self._dispatch.cut[node.lane, rid - 1])
                   else _fabric.LINK_CLASS_RESIDENT)
            _fabric.METER.set_link_class(me, them, cls)
            _fabric.METER.set_link_class(them, me, cls)

    # -- chaos surface -----------------------------------------------------

    def set_partitioned(self, node: KernelNode, cut: bool) -> None:
        """Device-side partition mask for one replica row (every link)."""
        with self.mu:
            if self._is_registered(node):
                self._dispatch.set_cut(node.lane, cut)
                self._note_link_classes(node)

    def set_link_hub_served(self, node: KernelNode, peer_rid: int,
                            cut: bool) -> None:
        """Cut (or heal) ONE mesh link, symmetrically: traffic between
        ``node``'s row and its group peer ``peer_rid`` leaves the mesh
        and rides the host hub — where transport faults (drop/delay)
        apply to it like any other hub traffic.  Both endpoints are
        masked together: hub fallback relies on the peer's sender-side
        mask to emit its half over the host (MeshDispatch.set_link_cut)."""
        if not (1 <= peer_rid <= self.spec.replicas):
            return
        with self.mu:
            if not self._is_registered(node):
                return
            self._dispatch.set_link_cut(node.lane, peer_rid, cut)
            peer = self._members.get(node.shard_id, {}).get(peer_rid)
            if peer is not None:
                self._dispatch.set_link_cut(
                    peer.lane, node.replica_id, cut)
            self._note_link_classes(node)

    def hub_accepts(self, node: KernelNode, m: pb.Message) -> bool:
        """NodeHost inbound gate for a mesh-resident replica: kernel-
        family traffic lands only when the hub is that link's carrier
        (link_hub_served); host-mediated traffic (snapshot streams and
        the like) always lands."""
        if m.type not in _KERNEL_MTYPES:
            return True
        return self.link_hub_served(node, int(m.from_))

    def link_hub_served(self, node: KernelNode, from_rid: int) -> bool:
        """True when the hub must deliver ``from_rid`` -> ``node``: the
        link is cut, or the sender is off-mesh/absent.  Resident links
        return False — the mesh already carried the message, so the hub
        copy (if any) is a stray and the NodeHost drops it."""
        if not (1 <= from_rid <= self.spec.replicas):
            return True
        if self._members.get(node.shard_id, {}).get(from_rid) is None:
            return True
        return bool(self._dispatch.cut[node.lane, from_rid - 1])

    # -- the step ----------------------------------------------------------

    def _make_dispatch(self):
        """The mesh backend (engine/dispatch.py MeshDispatch): donated +
        depth-1-pipelined shard_map dispatch through parallel/ici.py,
        with the carried inbox, pending counter and partition mask owned
        by the backend.  The step loop itself stays KernelEngine's —
        this seam is the ONLY dispatch-level difference."""
        from dragonboat_tpu.engine.dispatch import MeshDispatch

        return MeshDispatch(self.cluster)

    def _emit_messages(self, g, n, o, fl, pid, kind,
                       replicates, others) -> None:
        # intra-group messages ride the mesh inside the step; the host
        # sends ONLY the hub-fallback traffic of cut links (READ_INDEX
        # forwarding and snapshot streams go through the per-node host
        # path).  A witness peer needing a snapshot CANNOT be served
        # over the mesh (witness replicas are host-resident, their mesh
        # row is absent) — the group escalates to the host engines
        if fl[_F_WITSNAP] and o["s_wit_snap"][g].any():
            self._wit_snap_fallback.add(n.shard_id)
        cut = self._dispatch.cut[g]
        if not cut.any():
            return
        # hub fallback: rebuild EXACTLY the messages the mesh exchange
        # masked out (sender-side per-link mask, parallel/ici.py
        # _mask_outgoing reads the same unmasked output fields) and keep
        # only the ones addressed over cut links.  The wit_snap branch is
        # suppressed — it is host-escalation, handled above, not link
        # traffic.
        fl = fl.copy()
        fl[_F_WITSNAP] = False
        reps: list = []
        oths: list = []
        super()._emit_messages(g, n, o, fl, pid, kind, reps, oths)
        R = self.spec.replicas
        for built, dst in ((reps, replicates), (oths, others)):
            for item in built:
                to = item[1].to
                if 1 <= to <= R and cut[to - 1]:
                    dst.append(item)

    def _prop_target(self, n: KernelNode):
        """Forward proposals to the group's leader row (any NodeHost is a
        valid entry point, like the reference's MsgProp forwarding). Falls
        back to the proposer's own row when no leader is known — the
        kernel then drops and the client retries."""
        lane_cut = self._dispatch.cut[n.lane]
        if lane_cut.all():
            # a fully partitioned host's proposals must not tunnel
            # through shared memory to the leader row — stage on the cut
            # row, where the kernel drops them (the client sees DROPPED,
            # as it would against the reference's silenced transport)
            return n.lane, n
        lid = n._leader_cache
        if lid and lid != n.replica_id:
            leader = self._members.get(n.shard_id, {}).get(lid)
            # per-link discipline: forwarding IS a proposer->leader send,
            # so a cut link (or a fully cut leader row) blocks it — the
            # proposal stays on the proposer's row, the kernel drops it
            # there and the client retries
            if (leader is not None
                    and not lane_cut[lid - 1]
                    and not self._dispatch.cut[leader.lane].all()):
                return leader.lane, leader
        return n.lane, n

    # -- membership / escalation ------------------------------------------

    def update_lane_membership(self, node: KernelNode) -> None:
        """Refresh the peer books of EVERY row of this group from the RSM
        membership.  A membership the mesh cannot address (ids outside
        1..R, or more members than peer slots) evicts the whole group."""
        m = node.sm.get_membership()
        kp = self.kp
        ids = (list(m.addresses) + list(m.non_votings) + list(m.witnesses))
        if (len(ids) > kp.num_peers
                or any(not (1 <= r <= self.spec.replicas) for r in ids)):
            self._evict(node, reason=f"membership {sorted(ids)} outside "
                                     f"mesh addressing")
            return
        if m.witnesses:
            # witness replicas are never mesh-resident (their row stays
            # ABSENT), so mesh-routed traffic to them would vanish and
            # the ring floor would wait on their match forever — the
            # group serves witnesses from the host engines instead
            self._evict(node, reason="witness member on a mesh group")
            return
        s = self.state
        # the applied CC releases THIS replica's one-in-flight gate only
        # (pycore clears pending_config_change per replica at apply) — a
        # lagging follower's apply must not release the leader row's
        # gate while a newer CC is still uncommitted there
        s = s._replace(
            pending_cc=s.pending_cc.at[node.lane].set(False))
        # shared peer books: members apply the same CCs at different
        # steps, so only the NEWEST applied membership may write them —
        # a lagging member's view would roll the group's books back
        # (config_change_id is monotonic, membership.go ccid)
        last_ccid = self._books_ccid.get(node.shard_id, -1)
        if m.config_change_id >= last_ccid:
            self._books_ccid[node.shard_id] = m.config_change_id
            pids = np.zeros((kp.num_peers,), np.int32)
            kinds = np.zeros((kp.num_peers,), np.int32)
            i = 0
            for rid in sorted(m.addresses):
                pids[i], kinds[i] = rid, KP.K_VOTER
                i += 1
            for rid in sorted(m.non_votings):
                pids[i], kinds[i] = rid, KP.K_NON_VOTING
                i += 1
            with _capacity.METER.sanctioned("membership_up"):
                jp, jk = jax.numpy.asarray(pids), jax.numpy.asarray(kinds)
            for member in list(self._members.get(node.shard_id, {}).values()):
                s = s._replace(
                    pid=s.pid.at[member.lane].set(jp),
                    kind=s.kind.at[member.lane].set(jk),
                )
                self._kind_np[member.lane] = kinds
                self._pid_np[member.lane] = pids
        self.state = s

    def _evict(self, n: KernelNode, reason: str, carry=None) -> None:
        """Whole-group escalation: every member leaves the mesh and is
        rebuilt host-side by ITS OWN NodeHost; the group continues over
        the regular transport (all state is already durable)."""
        members = list(self._members.get(n.shard_id, {}).values())
        if not members:
            return
        _LOG.info("shard %d: leaving the mesh (%s)", n.shard_id, reason)
        for member in members:
            if self.remove_replica(member) is None:
                continue
            cb = getattr(member, "on_evict_cb", None)
            if cb is not None:
                cb(member, (carry or []) if member is n else [])


# ---------------------------------------------------------------------------
# process-wide registry: NodeHosts sharing a MeshSpec.name share one engine
# (the in-process form of hosts jointly executing one SPMD program)
# ---------------------------------------------------------------------------

_REGISTRY: dict[str, MeshEngine] = {}
_REG_MU = threading.Lock()


def attach_mesh_engine(kp: KP.KernelParams, spec: MeshSpec,
                       events=None, fleet_stats_every: int = 10,
                       pipeline_depth: int = 0,
                       health_top_k: int = 8,
                       health_thresholds=None,
                       invariant_probe: bool = True,
                       capacity_watermark_pct: float = 10.0,
                       capacity_budget_bytes: int = 0) -> MeshEngine:
    with _REG_MU:
        eng = _REGISTRY.get(spec.name)
        if eng is None:
            # the first attaching host's pipeline depth wins (the engine
            # is process-wide; geometry/kp mismatches raise below)
            eng = MeshEngine(kp, spec, events=events,
                             fleet_stats_every=fleet_stats_every,
                             pipeline_depth=pipeline_depth,
                             health_top_k=health_top_k,
                             health_thresholds=health_thresholds,
                             invariant_probe=invariant_probe,
                             capacity_watermark_pct=capacity_watermark_pct,
                             capacity_budget_bytes=capacity_budget_bytes)
            _REGISTRY[spec.name] = eng
        else:
            if eng.spec != spec:
                raise RuntimeError(
                    f"mesh '{spec.name}' geometry mismatch: engine has "
                    f"{eng.spec}, caller wants {spec}")
            if eng.kp != kp:
                raise RuntimeError(
                    f"mesh '{spec.name}' kernel params mismatch")
        eng._refs += 1
        return eng


def detach_mesh_engine(eng: MeshEngine) -> None:
    with _REG_MU:
        eng._refs -= 1
        if eng._refs <= 0:
            _REGISTRY.pop(eng.spec.name, None)
            # last host off the mesh: flush an env-armed profiler
            # capture now (KernelEngine.close semantics — the engine is
            # shared, so only full detach may stop it)
            eng.close()
