"""HLO budget gate: the step kernel's op counts stay within budget.

Lowers the self-driving bench loop (``bench_loop.run_steps``, the
20-step ``fori_loop`` over the full cluster step) with the **onehot**
ring-read config — the device-shaped graph — on the CPU backend, runs
XLA's optimization pipeline, and counts ``gather`` / ``scatter`` /
``while`` instructions in the optimized HLO.  Counts above the
checked-in ``analysis/hlo_budget.json`` fail the lint.

This turns the r5 gather prune (155 -> 32 gathers, PERF.md) into a
permanent gate: a change that reintroduces per-lane gathers or a
dynamic scatter — the exact op classes that serialize over [G] or
miscompile on TPU v5e — fails CI instead of waiting for the next
device bench window.

The same gate covers the mesh engines' dispatch graph: the ``mesh``
budget section lowers the fused shard_map serving step
(``parallel/ici.py jit_serve_step`` — kernel step + in-mesh routing +
partition mask in one body) on a 2-device host mesh and holds its
gather/scatter/while counts the same way, so neither the serial loop
nor the collective serving body can quietly regrow per-lane ops.

Counts are group-count-independent (instruction count, not instruction
size — verified 64 vs 1024 groups), so the gate measures at a small G
for speed.  The budget-update workflow when a kernel change
legitimately shifts the counts: run ``python scripts/lint.py
--reseed-hlo-budget``, review the diff of ``hlo_budget.json``, and
justify the new numbers in the PR alongside a PERF.md note.

The lowering path emits ``tracing.annotate`` spans (``lint.hlo.build``
/ ``lint.hlo.lower`` / ``lint.hlo.compile``) so a profiler capture of a
lint run attributes its cost like any other engine phase.

The ~10 s lower+compile dominates a full lint run, so its result is
cached in ``analysis/.hlo_budget_cache.json`` (gitignored) keyed by a
sha256 over the kernel-defining sources and the measurement config:
back-to-back runs with untouched sources reuse the cached counts, and
any edit to a hashed file invalidates the cache automatically.
"""

from __future__ import annotations

import hashlib
import json
import os

from dragonboat_tpu.analysis.common import Finding, rel

PASS = "hlo-budget"

BUDGET_FILE = "dragonboat_tpu/analysis/hlo_budget.json"
CACHE_FILE = "dragonboat_tpu/analysis/.hlo_budget_cache.json"

# every source whose edit can change the lowered step graph (or how it
# is counted) — hashed into the cache key
CACHE_SOURCES = (
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/params.py",
    "dragonboat_tpu/core/router.py",
    "dragonboat_tpu/parallel/ici.py",
    "dragonboat_tpu/bench_loop.py",
    "dragonboat_tpu/analysis/hlo_budget.py",
)

# Gated opcodes.  ``gather``/``scatter`` are the TPU-hostile op classes
# (PERF.md r2/r5); ``while`` bounds control-flow regions (the budget is
# 1 fori_loop + 4 inbox-family scans — an accidental lax.scan in a
# handler shows up here).
GATED_OPS = ("gather", "scatter", "while")


def _count_ops(hlo_text: str) -> dict[str, int]:
    """Instruction counts by opcode in HLO text.

    Opcode occurrences are counted as ``" <op>("`` which cannot collide
    with fused spellings (``all-gather(``, ``select-and-scatter(``,
    ``dynamic-update-slice(``) or with metadata paths (``while/body``).
    """
    ops = GATED_OPS + ("dynamic-slice", "dynamic-update-slice")
    return {op.replace("-", "_"): hlo_text.count(f" {op}(") for op in ops}


def measure(groups: int = 64, replicas: int = 3, iters: int = 20,
            onehot_reads: bool = True,
            entry: str = "run_steps") -> dict[str, int]:
    """Optimized-HLO op counts for a bench step loop on CPU.

    ``entry`` selects the traced loop: ``run_steps`` (the serial oracle)
    or ``run_steps_pipelined`` (PipelineConfig depth 1's fused
    double-step body) — both must stay inside their budgets so neither
    loop can quietly regrow per-lane gathers."""
    from dragonboat_tpu import tracing
    from dragonboat_tpu import bench_loop
    from dragonboat_tpu.bench_loop import bench_params, make_cluster
    from dragonboat_tpu.core.kstate import empty_inbox

    loop = getattr(bench_loop, entry)
    with tracing.annotate("lint.hlo.build"):
        # onehot_reads is keyed off the *target* platform; lowering runs
        # on CPU either way (JAX_PLATFORMS=cpu, set by the runner)
        kp = bench_params(replicas,
                          platform="tpu" if onehot_reads else "cpu")
        state = make_cluster(kp, groups, replicas)
        box = empty_inbox(kp, state.term.shape[0])
    with tracing.annotate("lint.hlo.lower"):
        lowered = loop.lower(kp, replicas, iters, True, True,
                             state, box)
    with tracing.annotate("lint.hlo.compile"):
        compiled = lowered.compile()
    return _count_ops(compiled.as_text())


def measure_mesh(groups: int = 4, replicas: int = 2,
                 onehot_reads: bool = True) -> dict[str, int]:
    """Optimized-HLO op counts of the fused shard_map serving body
    (``parallel/ici.py jit_serve_step``) — the mesh engines' dispatch
    entry — on a CPU host mesh.

    Needs ``replicas`` host devices; the lint runner forces
    ``xla_force_host_platform_device_count=2``, so the measurement mesh
    is ``('g','r') = (1, 2)``.  Instruction counts are group-count-
    independent exactly like the serial loop's, so the small mesh gates
    the same graph the 3-replica engines run."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from dragonboat_tpu import tracing
    from dragonboat_tpu.bench_loop import bench_params
    from dragonboat_tpu.parallel import ici

    devs = jax.devices()
    if len(devs) < replicas:
        raise RuntimeError(
            f"mesh HLO budget needs {replicas} devices, have "
            f"{len(devs)} — run via scripts/lint.py (it forces "
            "xla_force_host_platform_device_count)")
    with tracing.annotate("lint.hlo.build"):
        kp = bench_params(replicas,
                          platform="tpu" if onehot_reads else "cpu")
        mesh = Mesh(np.array(devs[:replicas]).reshape(1, replicas),
                    ("g", "r"))
        cluster, state, box = ici.make_ici_cluster(kp, mesh, groups)
        inp = cluster.shard(ici.self_driving_input(kp, state))
        cut = cluster.shard(
            jnp.zeros((cluster.total_rows, kp.num_peers), bool))
    with tracing.annotate("lint.hlo.lower"):
        lowered = ici.jit_serve_step.lower(
            kp, cluster, state, box, inp, cut)
    with tracing.annotate("lint.hlo.compile"):
        compiled = lowered.compile()
    return _count_ops(compiled.as_text())


def load_budget(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def source_hash(root: str, cfg: dict | None = None) -> str:
    """sha256 over the kernel-defining sources + measurement config +
    the jax version (a compiler upgrade changes the optimized HLO even
    when no repo source moved — the cache must not outlive it)."""
    import jax

    h = hashlib.sha256()
    h.update(("jax:" + getattr(jax, "__version__", "unknown")).encode())
    for src in CACHE_SOURCES:
        p = os.path.join(root, src)
        h.update(src.encode())
        if os.path.exists(p):
            with open(p, "rb") as f:
                h.update(f.read())
        else:
            h.update(b"<missing>")
    h.update(json.dumps(cfg or {}, sort_keys=True).encode())
    return h.hexdigest()


def _cache_load(root: str, key: str) -> dict[str, int] | None:
    path = os.path.join(root, CACHE_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            cache = json.load(f)
    except (OSError, ValueError):
        return None
    if cache.get("source_hash") != key:
        return None
    measured = cache.get("measured")
    return measured if isinstance(measured, dict) else None


def _cache_store(root: str, key: str, measured: dict[str, int]) -> None:
    path = os.path.join(root, CACHE_FILE)
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"source_hash": key, "measured": measured}, f,
                      indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass  # cache is best-effort; the lint result never depends on it


def run(root: str, budget_path: str | None = None,
        measured: dict[str, int] | None = None) -> list[Finding]:
    """Gate ``measured`` (or a fresh measurement) against the budget.

    Gates BOTH traced loops when the budget file declares them: the
    serial ``run_steps`` budget lives at the top level (the original
    schema), the pipelined loop's under ``"pipelined"``.  A flat
    ``measured`` dict passed by a caller gates the serial entry only."""
    path = budget_path or os.path.join(root, BUDGET_FILE)
    relpath = rel(root, path)
    if not os.path.exists(path):
        return [Finding(PASS, relpath, 1, "HB000",
                        "budget file missing — run scripts/lint.py "
                        "--reseed-hlo-budget to seed it")]
    spec = load_budget(path)
    cfg = spec.get("config", {})
    sections: dict[str, dict] = {"run_steps": spec.get("budget", {})}
    if "pipelined" in spec:
        sections["run_steps_pipelined"] = spec["pipelined"].get("budget", {})
    if "mesh" in spec:
        sections["serve_step"] = spec["mesh"].get("budget", {})
    if measured is not None:
        measured_map = {"run_steps": measured}
    else:
        key = source_hash(root, cfg)
        cached = _cache_load(root, key)
        if cached is not None and set(sections) <= set(cached):
            measured_map = cached
        else:
            mesh_cfg = spec.get("mesh", {}).get("config", {})

            def _measure_entry(entry: str) -> dict[str, int]:
                if entry == "serve_step":
                    return measure_mesh(
                        groups=mesh_cfg.get("groups", 4),
                        replicas=mesh_cfg.get("replicas", 2),
                        onehot_reads=cfg.get("onehot_reads", True))
                return measure(
                    groups=cfg.get("groups", 64),
                    replicas=cfg.get("replicas", 3),
                    iters=cfg.get("iters", 20),
                    onehot_reads=cfg.get("onehot_reads", True),
                    entry=entry)

            measured_map = {entry: _measure_entry(entry)
                            for entry in sections}
            _cache_store(root, key, measured_map)
    findings = []
    for entry, budget in sections.items():
        got_map = measured_map.get(entry)
        if got_map is None:
            continue
        tag = "" if entry == "run_steps" else f" [{entry}]"
        for op in GATED_OPS:
            key = op.replace("-", "_")
            limit = budget.get(key)
            got = got_map.get(key, 0)
            if limit is not None and got > limit:
                findings.append(Finding(
                    PASS, relpath, 1, "HB001",
                    f"optimized-HLO `{op}` count{tag} {got} exceeds budget "
                    f"{limit} (the kernel regressed toward per-lane {op}s; "
                    "if the change is justified, --reseed-hlo-budget and "
                    "record why in PERF.md)"))
    return findings


def reseed(root: str, budget_path: str | None = None,
           groups: int = 64, replicas: int = 3, iters: int = 20,
           onehot_reads: bool = True) -> dict:
    """Measure and (re)write the budget file; returns the new spec."""
    path = budget_path or os.path.join(root, BUDGET_FILE)
    measured = measure(groups=groups, replicas=replicas, iters=iters,
                       onehot_reads=onehot_reads)
    measured_pipe = measure(groups=groups, replicas=replicas, iters=iters,
                            onehot_reads=onehot_reads,
                            entry="run_steps_pipelined")
    mesh_groups, mesh_replicas = 4, 2
    measured_mesh = measure_mesh(groups=mesh_groups,
                                 replicas=mesh_replicas,
                                 onehot_reads=onehot_reads)
    spec = {
        "config": {
            "kernel": "bench_loop.run_steps",
            "groups": groups,
            "replicas": replicas,
            "iters": iters,
            "onehot_reads": onehot_reads,
            "platform": "cpu",
            "stage": "optimized HLO (compiled.as_text())",
        },
        "budget": {op.replace("-", "_"): measured[op.replace("-", "_")]
                   for op in GATED_OPS},
        "observed": measured,
        "pipelined": {
            "kernel": "bench_loop.run_steps_pipelined",
            "budget": {op.replace("-", "_"):
                       measured_pipe[op.replace("-", "_")]
                       for op in GATED_OPS},
            "observed": measured_pipe,
        },
        "mesh": {
            "kernel": "parallel/ici.py jit_serve_step (shard_map body)",
            "config": {"groups": mesh_groups,
                       "replicas": mesh_replicas,
                       "mesh": "('g','r') = (1, 2)"},
            "budget": {op.replace("-", "_"):
                       measured_mesh[op.replace("-", "_")]
                       for op in GATED_OPS},
            "observed": measured_mesh,
        },
        "note": ("Budgets gate gather/scatter/while over every traced "
                 "dispatch graph: serial run_steps at the top level, "
                 "the fused depth-1 run_steps_pipelined under "
                 "'pipelined', and the fused shard_map serving step "
                 "(the mesh engines' dispatch entry) under 'mesh'; "
                 "counts are group-count-independent.  Update via "
                 "scripts/lint.py --reseed-hlo-budget + a PERF.md note "
                 "justifying the change."),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(spec, f, indent=2, sort_keys=True)
        f.write("\n")
    _cache_store(root, source_hash(root, spec["config"]),
                 {"run_steps": measured,
                  "run_steps_pipelined": measured_pipe,
                  "serve_step": measured_mesh})
    return spec
