"""HLO budget gate: the step kernel's op counts stay within budget.

Lowers the self-driving bench loop (``bench_loop.run_steps``, the
20-step ``fori_loop`` over the full cluster step) with the **onehot**
ring-read config — the device-shaped graph — on the CPU backend, runs
XLA's optimization pipeline, and counts ``gather`` / ``scatter`` /
``while`` instructions in the optimized HLO.  Counts above the
checked-in ``analysis/hlo_budget.json`` fail the lint.

This turns the r5 gather prune (155 -> 32 gathers, PERF.md) into a
permanent gate: a change that reintroduces per-lane gathers or a
dynamic scatter — the exact op classes that serialize over [G] or
miscompile on TPU v5e — fails CI instead of waiting for the next
device bench window.

Counts are group-count-independent (instruction count, not instruction
size — verified 64 vs 1024 groups), so the gate measures at a small G
for speed.  The budget-update workflow when a kernel change
legitimately shifts the counts: run ``python scripts/lint.py
--reseed-hlo-budget``, review the diff of ``hlo_budget.json``, and
justify the new numbers in the PR alongside a PERF.md note.

The lowering path emits ``tracing.annotate`` spans (``lint.hlo.build``
/ ``lint.hlo.lower`` / ``lint.hlo.compile``) so a profiler capture of a
lint run attributes its cost like any other engine phase.
"""

from __future__ import annotations

import json
import os

from dragonboat_tpu.analysis.common import Finding, rel

PASS = "hlo-budget"

BUDGET_FILE = "dragonboat_tpu/analysis/hlo_budget.json"

# Gated opcodes.  ``gather``/``scatter`` are the TPU-hostile op classes
# (PERF.md r2/r5); ``while`` bounds control-flow regions (the budget is
# 1 fori_loop + 4 inbox-family scans — an accidental lax.scan in a
# handler shows up here).
GATED_OPS = ("gather", "scatter", "while")


def _count_ops(hlo_text: str) -> dict[str, int]:
    """Instruction counts by opcode in HLO text.

    Opcode occurrences are counted as ``" <op>("`` which cannot collide
    with fused spellings (``all-gather(``, ``select-and-scatter(``,
    ``dynamic-update-slice(``) or with metadata paths (``while/body``).
    """
    ops = GATED_OPS + ("dynamic-slice", "dynamic-update-slice")
    return {op.replace("-", "_"): hlo_text.count(f" {op}(") for op in ops}


def measure(groups: int = 64, replicas: int = 3, iters: int = 20,
            onehot_reads: bool = True) -> dict[str, int]:
    """Optimized-HLO op counts for the bench step loop on CPU."""
    from dragonboat_tpu import tracing
    from dragonboat_tpu.bench_loop import (
        bench_params,
        make_cluster,
        run_steps,
    )
    from dragonboat_tpu.core.kstate import empty_inbox

    with tracing.annotate("lint.hlo.build"):
        # onehot_reads is keyed off the *target* platform; lowering runs
        # on CPU either way (JAX_PLATFORMS=cpu, set by the runner)
        kp = bench_params(replicas,
                          platform="tpu" if onehot_reads else "cpu")
        state = make_cluster(kp, groups, replicas)
        box = empty_inbox(kp, state.term.shape[0])
    with tracing.annotate("lint.hlo.lower"):
        lowered = run_steps.lower(kp, replicas, iters, True, True,
                                  state, box)
    with tracing.annotate("lint.hlo.compile"):
        compiled = lowered.compile()
    return _count_ops(compiled.as_text())


def load_budget(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def run(root: str, budget_path: str | None = None,
        measured: dict[str, int] | None = None) -> list[Finding]:
    """Gate ``measured`` (or a fresh measurement) against the budget."""
    path = budget_path or os.path.join(root, BUDGET_FILE)
    relpath = rel(root, path)
    if not os.path.exists(path):
        return [Finding(PASS, relpath, 1, "HB000",
                        "budget file missing — run scripts/lint.py "
                        "--reseed-hlo-budget to seed it")]
    spec = load_budget(path)
    cfg = spec.get("config", {})
    if measured is None:
        measured = measure(
            groups=cfg.get("groups", 64),
            replicas=cfg.get("replicas", 3),
            iters=cfg.get("iters", 20),
            onehot_reads=cfg.get("onehot_reads", True))
    findings = []
    for op in GATED_OPS:
        key = op.replace("-", "_")
        limit = spec["budget"].get(key)
        got = measured.get(key, 0)
        if limit is not None and got > limit:
            findings.append(Finding(
                PASS, relpath, 1, "HB001",
                f"optimized-HLO `{op}` count {got} exceeds budget {limit} "
                f"(the kernel regressed toward per-lane {op}s; if the "
                "change is justified, --reseed-hlo-budget and record why "
                "in PERF.md)"))
    return findings


def reseed(root: str, budget_path: str | None = None,
           groups: int = 64, replicas: int = 3, iters: int = 20,
           onehot_reads: bool = True) -> dict:
    """Measure and (re)write the budget file; returns the new spec."""
    path = budget_path or os.path.join(root, BUDGET_FILE)
    measured = measure(groups=groups, replicas=replicas, iters=iters,
                       onehot_reads=onehot_reads)
    spec = {
        "config": {
            "kernel": "bench_loop.run_steps",
            "groups": groups,
            "replicas": replicas,
            "iters": iters,
            "onehot_reads": onehot_reads,
            "platform": "cpu",
            "stage": "optimized HLO (compiled.as_text())",
        },
        "budget": {op.replace("-", "_"): measured[op.replace("-", "_")]
                   for op in GATED_OPS},
        "observed": measured,
        "note": ("Budgets gate gather/scatter/while; counts are "
                 "group-count-independent.  Update via scripts/lint.py "
                 "--reseed-hlo-budget + a PERF.md note justifying the "
                 "change."),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(spec, f, indent=2, sort_keys=True)
        f.write("\n")
    return spec
