"""Shared lint plumbing: findings, waivers, reporting.

Waivers live in ``analysis/waivers.toml``.  The container pins Python
3.10 (no ``tomllib``) and the repo takes no third-party deps, so the
loader reads the narrow TOML subset the file actually uses:
``[[waiver]]`` array-of-tables with quoted-string values and ``#``
comments.  The format stays real TOML so a 3.11 toolchain can parse the
same file.
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    """One lint hit, addressed by repo-relative path."""

    pass_name: str   # "tracer-safety" | "hlo-budget" | "concurrency" | ...
    path: str        # repo-relative, forward slashes
    line: int
    rule: str        # short id, e.g. "TS001"
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Waiver:
    pass_name: str
    path: str                      # fnmatch pattern on the relative path
    reason: str
    rule: str | None = None
    contains: str | None = None    # substring of the finding message
    hits: int = field(default=0, compare=False)
    line: int = field(default=0, compare=False)  # [[waiver]] line in the toml

    def matches(self, f: Finding) -> bool:
        if self.pass_name != f.pass_name:
            return False
        if not fnmatch.fnmatch(f.path, self.path):
            return False
        if self.rule is not None and self.rule != f.rule:
            return False
        if self.contains is not None and self.contains not in f.message:
            return False
        return True


class WaiverError(ValueError):
    pass


def _parse_toml_subset(text: str, where: str) -> list[dict]:
    """``[[waiver]]`` tables of ``key = "string"`` pairs; nothing else."""
    tables: list[dict] = []
    cur: dict | None = None
    for n, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            cur = {"__line__": n}
            tables.append(cur)
            continue
        if "=" in line and cur is not None:
            key, _, val = line.partition("=")
            key, val = key.strip(), val.strip()
            # strip a trailing comment outside the quotes
            if len(val) >= 2 and val[0] in "\"'":
                q = val[0]
                end = val.find(q, 1)
                if end < 0:
                    raise WaiverError(f"{where}:{n}: unterminated string")
                cur[key] = val[1:end]
                continue
        raise WaiverError(f"{where}:{n}: unsupported syntax {line!r} "
                          "(only [[waiver]] tables of quoted strings)")
    return tables


def load_waivers(path: str) -> list[Waiver]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        tables = _parse_toml_subset(f.read(), os.path.basename(path))
    waivers = []
    for i, t in enumerate(tables):
        missing = {"pass_name", "path", "reason"} - set(t)
        if missing:
            raise WaiverError(
                f"waiver #{i + 1} missing keys: {sorted(missing)}")
        if not t["reason"].strip():
            raise WaiverError(f"waiver #{i + 1}: empty reason")
        waivers.append(Waiver(pass_name=t["pass_name"], path=t["path"],
                              reason=t["reason"], rule=t.get("rule"),
                              contains=t.get("contains"),
                              line=t.get("__line__", 0)))
    return waivers


def apply_waivers(findings: list[Finding], waivers: list[Waiver]
                  ) -> tuple[list[Finding], list[tuple[Finding, Waiver]]]:
    """-> (unwaived, [(waived finding, its waiver)]).  First match wins."""
    unwaived: list[Finding] = []
    waived: list[tuple[Finding, Waiver]] = []
    for f in findings:
        for w in waivers:
            if w.matches(f):
                w.hits += 1
                waived.append((f, w))
                break
        else:
            unwaived.append(f)
    return unwaived, waived


def rel(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


# ---------------------------------------------------------------------------
# Field-contract grammar (core/kstate.py CONTRACTS) + the tiny shape/dtype
# lattice the contracts pass interprets over.  Kept here so tests and any
# future pass share one parser.
# ---------------------------------------------------------------------------

#: canonical dtype names used throughout the contracts pass
DTYPES = ("i32", "u32", "f32", "bool")


class ContractError(ValueError):
    pass


#: legal values of the ``part=`` tag: data that lives per-group and must be
#: sharded along the mesh G axis, vs data that is identical on every device
PARTS = ("G", "replicated")

#: legal values of the ``collective=`` tag: ``declared`` marks a struct whose
#: fields are PRODUCED by an intentional cross-G collective (fleet stats);
#: ``none`` (the default) means cross-G data flow into the field is a bug
COLLECTIVES = ("none", "declared")


@dataclass(frozen=True)
class FieldContract:
    """One parsed ``"[G, P] i32 domain=A..B ring optional part=G"`` string."""

    axes: tuple[str, ...]          # symbolic axis names, () = scalar
    dtype: str                     # one of DTYPES
    ring: bool = False             # power-of-two ring: indexing must mask
    optional: bool = False         # field may be None under some configs
    domain: tuple[str, str] | None = None  # (lo_name, hi_name) in params.py
    part: str | None = None        # one of PARTS, None = undeclared
    collective: str | None = None  # one of COLLECTIVES, None = undeclared


def parse_contract(spec: str, where: str = "<contract>") -> FieldContract:
    """Parse one contract string; raises ContractError on bad grammar."""
    s = spec.strip()
    if not s.startswith("["):
        raise ContractError(f"{where}: contract must start with [axes]: "
                            f"{spec!r}")
    end = s.find("]")
    if end < 0:
        raise ContractError(f"{where}: unterminated axis list: {spec!r}")
    axes_src = s[1:end].strip()
    axes = tuple(a.strip() for a in axes_src.split(",") if a.strip())
    rest = s[end + 1:].split()
    if not rest:
        raise ContractError(f"{where}: missing dtype: {spec!r}")
    dtype, tags = rest[0], rest[1:]
    if dtype not in DTYPES:
        raise ContractError(f"{where}: unknown dtype {dtype!r} "
                            f"(want one of {DTYPES}): {spec!r}")
    ring = optional = False
    domain = None
    part = collective = None
    for t in tags:
        if t == "ring":
            ring = True
        elif t == "optional":
            optional = True
        elif t.startswith("domain="):
            lo, sep, hi = t[len("domain="):].partition("..")
            if not sep or not lo or not hi:
                raise ContractError(f"{where}: bad domain tag {t!r} "
                                    "(want domain=LO..HI)")
            domain = (lo, hi)
        elif t.startswith("part="):
            part = t[len("part="):]
            if part not in PARTS:
                raise ContractError(f"{where}: bad part tag {t!r} "
                                    f"(want part={'|'.join(PARTS)})")
        elif t.startswith("collective="):
            collective = t[len("collective="):]
            if collective not in COLLECTIVES:
                raise ContractError(
                    f"{where}: bad collective tag {t!r} "
                    f"(want collective={'|'.join(COLLECTIVES)})")
        else:
            raise ContractError(f"{where}: unknown tag {t!r}: {spec!r}")
    return FieldContract(axes=axes, dtype=dtype, ring=ring,
                         optional=optional, domain=domain,
                         part=part, collective=collective)


def parse_contracts(table: dict, where: str = "<contracts>"
                    ) -> dict[str, dict[str, FieldContract]]:
    """Parse a ``{"Class": {"field": "spec", ...}, ...}`` literal."""
    out: dict[str, dict[str, FieldContract]] = {}
    for cls, fields in table.items():
        out[cls] = {
            name: parse_contract(spec, f"{where}:{cls}.{name}")
            for name, spec in fields.items()
        }
    return out


def broadcast_axes(a: tuple[str, ...] | None, b: tuple[str, ...] | None
                   ) -> tuple[tuple[str, ...] | None, str | None]:
    """NumPy trailing-aligned broadcast over NAMED axes.

    Axis entries are axis names, ``'1'`` (unit, broadcasts into anything)
    or ``'?'`` (unknown extent, unifies with anything).  ``None`` means a
    fully unknown rank/shape.  Returns ``(result_axes, conflict)`` where
    ``conflict`` is a human-readable description of the first pair of
    distinct named axes forced into alignment, or ``None`` if the
    broadcast is clean.
    """
    if a is None and b is None:
        return None, None
    if a is None or b is None:
        # unknown rank/shape unifies with the known side (optimistic:
        # the lattice never flags what it cannot see)
        return (b if a is None else a), None
    out: list[str] = []
    conflict = None
    for i in range(1, max(len(a), len(b)) + 1):
        x = a[-i] if i <= len(a) else "1"
        y = b[-i] if i <= len(b) else "1"
        if x == y:
            out.append(x)
        elif x == "1":
            out.append(y)
        elif y == "1":
            out.append(x)
        elif x == "?" or y == "?":
            out.append(y if x == "?" else x)
        else:
            # two distinct NAMED axes aligned — the broadcast "works"
            # numerically whenever the extents happen to agree (K == E
            # == B == RI == 8 in the default geometry), which is exactly
            # the silent cross-axis bug this lattice exists to catch.
            conflict = f"axis {x!r} vs {y!r} at dim -{i}"
            out.append("?")
    return tuple(reversed(out)), conflict


def join_dtypes(a: str | None, b: str | None) -> str | None:
    """Lattice join for ``jnp.where``-style merges: agree or unknown."""
    if a is None or b is None:
        return None
    return a if a == b else None


# ---------------------------------------------------------------------------
# Protocol-invariant grammar (core/kstate.py INVARIANTS) — machine-readable
# cross-field per-group invariants over ShardState, consumed by three legs:
# the static safety pass (analysis/safety.py), the small-scope model checker
# (scripts/model_check.py) and the runtime probe (core/invariants.py).
#
# Grammar, one string per invariant:
#
#   invariant  := [ guard ( "&" guard )* "=>" ] comparison
#   guard      := comparison
#   comparison := term OP term
#   OP         := "<=" | ">=" | "==" | "!=" | "<" | ">"
#   term       := FIELD | "prev." FIELD | "quorum(" FIELD ")" | INT | CONST
#
# FIELD is a ShardState field name (per-group [G] column, or [G, P] for
# quorum()); ``prev.`` reads the field at the previous observation (making
# the invariant STEP-scoped — checked over a transition — instead of
# STATE-scoped); ``quorum(f)`` is the sorted-quorum reduction over the
# [G, P] peer column f, exactly core/kernel.py _sorted_match_quorum_index;
# CONST is an UPPERCASE constant resolved in core/params.py (e.g. LEADER).
# ---------------------------------------------------------------------------

#: comparison operators, longest-match-first for the scanner
INVARIANT_OPS = ("<=", ">=", "==", "!=", "<", ">")


class InvariantError(ValueError):
    pass


@dataclass(frozen=True)
class InvTerm:
    """One operand: kind ∈ field | prev | quorum | const | param."""

    kind: str
    name: str | None = None    # field name (field/prev/quorum) or param name
    value: int | None = None   # const only


@dataclass(frozen=True)
class InvCompare:
    lhs: InvTerm
    op: str                    # one of INVARIANT_OPS
    rhs: InvTerm


@dataclass(frozen=True)
class Invariant:
    """One parsed invariant: ``all(guards) => conclusion`` per group row."""

    name: str
    guards: tuple[InvCompare, ...]
    conclusion: InvCompare
    scope: str                 # "state" | "step" (any prev. term => step)
    fields: tuple[str, ...]    # every ShardState field referenced (sorted)


def _parse_inv_term(src: str, where: str) -> InvTerm:
    s = src.strip()
    if not s:
        raise InvariantError(f"{where}: empty term")
    if s.lstrip("-").isdigit():
        return InvTerm(kind="const", value=int(s))
    if s.startswith("prev."):
        name = s[len("prev."):]
        if not name.isidentifier():
            raise InvariantError(f"{where}: bad prev. field {s!r}")
        return InvTerm(kind="prev", name=name)
    if s.startswith("quorum(") and s.endswith(")"):
        name = s[len("quorum("):-1].strip()
        if not name.isidentifier():
            raise InvariantError(f"{where}: bad quorum() field {s!r}")
        return InvTerm(kind="quorum", name=name)
    if not s.isidentifier():
        raise InvariantError(f"{where}: unparsable term {s!r}")
    if s.isupper():
        return InvTerm(kind="param", name=s)
    return InvTerm(kind="field", name=s)


def _parse_inv_compare(src: str, where: str) -> InvCompare:
    s = src.strip()
    for op in INVARIANT_OPS:
        # scan for the operator outside any quorum(...) parens; ops never
        # appear inside a term, so a plain find is enough — but prefer the
        # longest operator (<= before <) via the INVARIANT_OPS ordering
        idx = s.find(op)
        if idx > 0:
            lhs, rhs = s[:idx], s[idx + len(op):]
            return InvCompare(lhs=_parse_inv_term(lhs, where), op=op,
                              rhs=_parse_inv_term(rhs, where))
    raise InvariantError(f"{where}: no comparison operator in {src!r} "
                         f"(want one of {INVARIANT_OPS})")


def parse_invariant(name: str, spec: str,
                    where: str = "<invariant>") -> Invariant:
    """Parse one ``[guard & ... =>] lhs OP rhs`` string."""
    w = f"{where}:{name}"
    s = spec.strip()
    if "=>" in s:
        guard_src, _, concl_src = s.partition("=>")
        guards = tuple(_parse_inv_compare(g, w)
                       for g in guard_src.split("&") if g.strip())
        if not guards:
            raise InvariantError(f"{w}: '=>' with no guards: {spec!r}")
    else:
        guards, concl_src = (), s
    concl = _parse_inv_compare(concl_src, w)
    terms = [t for c in (*guards, concl) for t in (c.lhs, c.rhs)]
    scope = ("step" if any(t.kind == "prev" for t in terms) else "state")
    fields = tuple(sorted({t.name for t in terms
                           if t.kind in ("field", "prev", "quorum")}))
    if not fields:
        raise InvariantError(f"{w}: invariant references no field: {spec!r}")
    return Invariant(name=name, guards=guards, conclusion=concl,
                     scope=scope, fields=fields)


def parse_invariants(table: dict, where: str = "<invariants>"
                     ) -> dict[str, Invariant]:
    """Parse an ``{"name": "spec", ...}`` literal (kstate.py INVARIANTS)."""
    return {name: parse_invariant(name, spec, where)
            for name, spec in table.items()}
