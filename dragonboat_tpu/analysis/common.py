"""Shared lint plumbing: findings, waivers, reporting.

Waivers live in ``analysis/waivers.toml``.  The container pins Python
3.10 (no ``tomllib``) and the repo takes no third-party deps, so the
loader reads the narrow TOML subset the file actually uses:
``[[waiver]]`` array-of-tables with quoted-string values and ``#``
comments.  The format stays real TOML so a 3.11 toolchain can parse the
same file.
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    """One lint hit, addressed by repo-relative path."""

    pass_name: str   # "tracer-safety" | "hlo-budget" | "concurrency" | ...
    path: str        # repo-relative, forward slashes
    line: int
    rule: str        # short id, e.g. "TS001"
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Waiver:
    pass_name: str
    path: str                      # fnmatch pattern on the relative path
    reason: str
    rule: str | None = None
    contains: str | None = None    # substring of the finding message
    hits: int = field(default=0, compare=False)

    def matches(self, f: Finding) -> bool:
        if self.pass_name != f.pass_name:
            return False
        if not fnmatch.fnmatch(f.path, self.path):
            return False
        if self.rule is not None and self.rule != f.rule:
            return False
        if self.contains is not None and self.contains not in f.message:
            return False
        return True


class WaiverError(ValueError):
    pass


def _parse_toml_subset(text: str, where: str) -> list[dict]:
    """``[[waiver]]`` tables of ``key = "string"`` pairs; nothing else."""
    tables: list[dict] = []
    cur: dict | None = None
    for n, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            cur = {}
            tables.append(cur)
            continue
        if "=" in line and cur is not None:
            key, _, val = line.partition("=")
            key, val = key.strip(), val.strip()
            # strip a trailing comment outside the quotes
            if len(val) >= 2 and val[0] in "\"'":
                q = val[0]
                end = val.find(q, 1)
                if end < 0:
                    raise WaiverError(f"{where}:{n}: unterminated string")
                cur[key] = val[1:end]
                continue
        raise WaiverError(f"{where}:{n}: unsupported syntax {line!r} "
                          "(only [[waiver]] tables of quoted strings)")
    return tables


def load_waivers(path: str) -> list[Waiver]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        tables = _parse_toml_subset(f.read(), os.path.basename(path))
    waivers = []
    for i, t in enumerate(tables):
        missing = {"pass_name", "path", "reason"} - set(t)
        if missing:
            raise WaiverError(
                f"waiver #{i + 1} missing keys: {sorted(missing)}")
        if not t["reason"].strip():
            raise WaiverError(f"waiver #{i + 1}: empty reason")
        waivers.append(Waiver(pass_name=t["pass_name"], path=t["path"],
                              reason=t["reason"], rule=t.get("rule"),
                              contains=t.get("contains")))
    return waivers


def apply_waivers(findings: list[Finding], waivers: list[Waiver]
                  ) -> tuple[list[Finding], list[tuple[Finding, Waiver]]]:
    """-> (unwaived, [(waived finding, its waiver)]).  First match wins."""
    unwaived: list[Finding] = []
    waived: list[tuple[Finding, Waiver]] = []
    for f in findings:
        for w in waivers:
            if w.matches(f):
                w.hits += 1
                waived.append((f, w))
                break
        else:
            unwaived.append(f)
    return unwaived, waived


def rel(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")
