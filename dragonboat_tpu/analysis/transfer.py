"""Transfer-boundary analyzer: the device<->host seam as a checked
contract.

ROADMAP item 2 ("device-resident message fabric: zero host hops in the
commit path") needs an inventory before it can drive anything to zero:
which values cross the jit seam per step, in which direction, and how
many bytes they carry.  The partition pass's PS006 catches *implicit*
syncs in a handful of hot methods; nothing classifies the *sanctioned*
crossings, sizes them, or stops them from regrowing — the same gap
hlo-budget closed for op counts, closed here for transfers.

Source of truth is ``engine/dispatch.py``'s two machine-read literals:

- ``TRANSFER_LEDGER`` — per jit entry (``DISPATCH_ENTRIES`` plus the
  telemetry reductions), the device-resident operand classes, every
  host->device upload row and every device->host download row, each
  with the host qualname performing the crossing and the
  ``capacity.METER`` tag it counts under;
- ``SYNC_POINTS`` — the only engine-layer qualnames whose bodies may
  force a device value (``int()`` / ``.item()`` / ``np.asarray`` /
  ``block_until_ready``).

Every row is sized in closed form from the CONTRACTS grammar
(``capacity.bytes_for_contract`` — class names resolve through the
merged kstate/fleet/health/invariants tables, inline ``"[G, K] i32"``
strings directly), and the per-step up/down totals are gated against
``analysis/transfer_budget.json`` exactly like the hlo-budget gate.

Rules:

- TB001  undeclared crossing: a dispatch entry with no ledger section,
         an entry array parameter no resident/upload row covers, a
         ledger row whose site qualname does not exist in the engine
         layer, an unsizable row, or (dynamic) a METER tag observed
         live that no declaration carries
- TB002  per-step upload/download bytes exceed the seeded budget
- TB003  wide-field download outside the ``_LazyOut`` masked-fetch
         path: an unmasked download row carrying a [G, axis] field, or
         an eager ``np.asarray`` of a wide StepOutput field in engine
         code (the 42-field sweep the masked fetch deleted)
- TB004  upload not built through a staging builder: a
         ``jnp.asarray`` / ``jnp.array`` / ``jax.device_put`` in the
         engine layer outside every declared ledger site and every
         ``*.to_device`` builder
- TB005  device->host sync outside a declared ``SYNC_POINTS`` qualname
         (the engine-scope sharpening of PS006: the scan covers EVERY
         engine-layer function, not just the hot-path list)
- TB006  per-step transfer count growth: more per-step crossings than
         the ledger declares (static vs budget, and dynamic — the live
         METER counts diffed against the ledger after a guarded step
         loop at three geometries: serial depth-0, serial depth-1
         donated, 2-device mesh)

The dynamic leg drives the REAL seam objects (``SerialDispatch`` /
``MeshDispatch`` + the staging builders) under
``capacity.METER.guard()`` — ``jax.transfer_guard("disallow")`` with
declared sync points re-allowed via scoped guards — so an implicit
transfer raises at the JAX level while the tag counters prove the
declared crossings happen EXACTLY as often as the ledger says.  Results
are cached in ``.transfer_cache.json`` keyed on ``jax.__version__`` +
the seam sources, mirroring the partition pass.

The pass's artifact — ``build/transfer_ledger.json``, every crossing
with bytes and provenance — is literally ROADMAP item 2's work-list:
the rows it enumerates are the host hops the device-resident fabric
must delete, and this gate is what keeps them deleted.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os

from dragonboat_tpu.analysis.common import (
    ContractError,
    Finding,
    parse_contract,
    rel,
)

PASS = "transfer"

DISPATCH_FILE = "dragonboat_tpu/engine/dispatch.py"
BUDGET_FILE = "dragonboat_tpu/analysis/transfer_budget.json"
CACHE_FILE = "dragonboat_tpu/analysis/.transfer_cache.json"
LEDGER_ARTIFACT = "build/transfer_ledger.json"

#: the engine layer: every file whose code may touch the boundary
ENGINE_FILES = (
    "dragonboat_tpu/engine/kernel_engine.py",
    "dragonboat_tpu/engine/mesh_engine.py",
    "dragonboat_tpu/engine/dispatch.py",
)
#: contract tables the sizing model merges
CONTRACT_FILES = (
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/fleet.py",
    "dragonboat_tpu/core/health.py",
    "dragonboat_tpu/core/invariants.py",
)

#: every file any leg reads — scripts/lint.py --changed-only scope
SCOPE = ENGINE_FILES + CONTRACT_FILES + (
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/params.py",
    "dragonboat_tpu/parallel/ici.py",
    "dragonboat_tpu/capacity.py",
    BUDGET_FILE,
)

#: sources hashed into the dynamic-leg cache key (an edit to any seam
#: source, or a jax upgrade, invalidates the cached live diff)
CACHE_SOURCES = SCOPE[:-1] + (
    "dragonboat_tpu/bench_loop.py",
    "dragonboat_tpu/analysis/transfer.py",
)

#: telemetry reductions classified alongside DISPATCH_ENTRIES: the
#: jitted impls whose signatures the TB001 parameter check reads
TELEMETRY_ENTRIES = {
    "fleet_stats": ("dragonboat_tpu/core/fleet.py", "_fleet_stats_impl"),
    "fleet_health": ("dragonboat_tpu/core/health.py", "_fleet_health_impl"),
    "check_invariants": ("dragonboat_tpu/core/invariants.py",
                         "_check_invariants_impl"),
}

#: entry parameters that are static/jit-metadata, never array crossings
STATIC_PARAMS = frozenset({
    "kp", "cluster", "cl", "replicas", "thresholds", "k",
})

#: conventional parameter name -> contract class (the partition pass's
#: mesh-level bindings, reused so the two passes cannot drift)
from dragonboat_tpu.analysis.partition import (  # noqa: E402
    PART_BINDINGS as PARAM_CLASSES,
    _DEVICE_PRODUCERS,
    _DEVICE_SELF_ATTRS,
)
from dragonboat_tpu.analysis import contracts as _ct  # noqa: E402

#: engine-held device trees beyond the partition pass's set (the lazy
#: output view and the telemetry digest carries)
_SELF_ATTRS = frozenset(_DEVICE_SELF_ATTRS) | {
    "_out", "_health_digest", "_inv_digest",
}

#: geometry the budget/ledger sizes at when no budget file declares one
#: (the bench sweet spot, bench_loop.bench_params(3) + 1024 groups)
DEFAULT_CONFIG = {
    "num_groups": 1024,
    "num_peers": 3,
    "log_cap": 128,
    "inbox_cap": 10,
    "msg_entries": 32,
    "proposal_cap": 32,
    "readindex_cap": 4,
    "inline_payloads": False,
    "top_k": 8,
}

#: host-side axis extents (histogram widths, report rows) — resolved
#: live from fleet/health/invariants when importable, else this frozen
#: snapshot keeps fixture runs sizable
_AXIS_ENV_FALLBACK = {
    "ROLES": 6, "LAGB": 9, "INBOXB": 6,
    "C": 5, "TOPK": 8, "RW": 13, "NI": 7,
}

#: dynamic-leg step count per geometry
_LIVE_STEPS = 5


class _Geom:
    """Attribute view of a config dict (stands in for KernelParams so
    fixture geometries never trip its power-of-two asserts)."""

    def __init__(self, cfg: dict) -> None:
        for k, v in cfg.items():
            setattr(self, k, v)


# ---------------------------------------------------------------------------
# declaration + source loading
# ---------------------------------------------------------------------------

_DECL_NAMES = ("SYNC_POINTS", "TRANSFER_LEDGER", "DISPATCH_ENTRIES")


def _load_decl(root: str) -> tuple[dict, dict[str, int], list[Finding]]:
    """The dispatch transfer literals (+ line numbers + load findings)."""
    decl: dict = {"SYNC_POINTS": {}, "TRANSFER_LEDGER": {},
                  "DISPATCH_ENTRIES": {}}
    lines = {name: 1 for name in _DECL_NAMES}
    findings: list[Finding] = []
    path = os.path.join(root, DISPATCH_FILE)
    if not os.path.exists(path):
        findings.append(Finding(
            PASS, DISPATCH_FILE, 1, "TB001",
            "engine/dispatch.py is missing — the transfer contract "
            "(SYNC_POINTS / TRANSFER_LEDGER) has no home"))
        return decl, lines, findings
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    seen = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name not in _DECL_NAMES:
            continue
        lines[name] = node.lineno
        seen.add(name)
        try:
            decl[name] = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            findings.append(Finding(
                PASS, DISPATCH_FILE, node.lineno, "TB001",
                f"{name} is not a pure literal — the transfer contract "
                "must be ast.literal_eval-parseable (no names, calls or "
                "comprehensions)"))
    for name in ("SYNC_POINTS", "TRANSFER_LEDGER"):
        if name not in seen:
            findings.append(Finding(
                PASS, DISPATCH_FILE, 1, "TB001",
                f"{name} literal missing from engine/dispatch.py — "
                "every boundary crossing must be declared there"))
    return decl, lines, findings


def _engine_paths(root: str, files: list[str] | None) -> list[str]:
    if files is None:
        return [os.path.join(root, f) for f in ENGINE_FILES]
    return [p if os.path.isabs(p) else os.path.join(root, p)
            for p in files if p.endswith(".py")]


def _parse(path: str) -> ast.Module | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=path)


def _qual_funcs(tree: ast.Module) -> list[tuple[str, ast.FunctionDef]]:
    """(qualname, def) for every module-level function and every method;
    nested defs belong to their enclosing method's qualname."""
    out: list[tuple[str, ast.FunctionDef]] = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append((f"{node.name}.{sub.name}", sub))
    return out


# ---------------------------------------------------------------------------
# sizing: contract tables + closed-form bytes per row
# ---------------------------------------------------------------------------


def _collect_contracts(trees: dict[str, ast.Module],
                       findings: list[Finding]) -> dict:
    """Merged ``{cls: {field: FieldContract}}`` from every CONTRACTS
    literal in the given trees (kstate + the telemetry modules)."""
    table: dict = {}
    for relpath, tree in trees.items():
        for node in tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "CONTRACTS"):
                continue
            try:
                raw = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue  # the contracts pass owns that diagnosis
            for cls, fields in raw.items():
                parsed = {}
                for fname, spec in fields.items():
                    try:
                        parsed[fname] = parse_contract(
                            spec, f"{relpath}:{cls}.{fname}")
                    except ContractError as e:
                        findings.append(Finding(
                            PASS, relpath, node.lineno, "TB001",
                            f"unsizable contract {cls}.{fname}: {e}"))
                table.setdefault(cls, {}).update(parsed)
    return table


def _axis_env(cfg: dict) -> dict:
    """Host-side axis extents for the report/histogram classes."""
    try:
        from dragonboat_tpu.core import fleet, health, invariants
        env = {
            "ROLES": len(fleet.ROLE_NAMES),
            "LAGB": len(fleet.bucket_labels(fleet.LAG_BUCKETS)),
            "INBOXB": len(fleet.bucket_labels(fleet.INBOX_BUCKETS)),
            "C": health.NUM_CLASSES,
            "TOPK": health.DEFAULT_TOP_K,
            "RW": health.ROW_WIDTH,
            "NI": invariants.NUM_INVARIANTS,
        }
    except ImportError:  # pragma: no cover - fixture environments
        env = dict(_AXIS_ENV_FALLBACK)
    env["TOPK"] = int(cfg.get("top_k", env["TOPK"]))
    return env


def _field_bytes(fc, kp, num_groups: int, env: dict) -> int:
    from dragonboat_tpu import capacity as _capacity

    n = _capacity.DTYPE_BYTES[fc.dtype]
    for ax in fc.axes:
        if ax == "G":
            n *= int(num_groups)
        elif ax.isdigit():
            n *= int(ax)
        elif ax in _capacity.AXIS_PARAMS:
            n *= int(getattr(kp, _capacity.AXIS_PARAMS[ax]))
        elif ax in env:
            n *= int(env[ax])
        else:
            raise ValueError(f"axis {ax!r} has no extent")
    return n


def _value_bytes(value: str, contracts: dict, kp, num_groups: int,
                 env: dict) -> int | None:
    """Closed-form bytes of one ledger row value: a contract class name
    (sum of its materialized fields) or an inline contract string."""
    from dragonboat_tpu import capacity as _capacity

    fields = contracts.get(value)
    if fields is not None:
        total = 0
        for fname, fc in fields.items():
            if fc.optional and not _capacity._optional_materialized(
                    value, fname, kp):
                continue
            try:
                total += _field_bytes(fc, kp, num_groups, env)
            except ValueError:
                return None
        return total
    try:
        return _capacity.bytes_for_contract(value, kp, num_groups,
                                            axis_extra=env)
    except (ValueError, ContractError):
        return None


def _ledger_rows(ledger: dict):
    """Every (entry, direction, row) in the ledger, ``_control``
    included (its rows carry an explicit ``dir``)."""
    for entry, section in ledger.items():
        if entry == "_control":
            for row in section:
                yield entry, row.get("dir", "up"), row
            continue
        for dirn in ("up", "down"):
            for row in section.get(dirn, ()):
                yield entry, dirn, row


def build_ledger(root: str, decl: dict | None = None,
                 cfg: dict | None = None,
                 contracts: dict | None = None) -> dict:
    """The sized transfer ledger: every declared crossing with closed-
    form bytes at ``cfg``'s geometry, plus the per-step profile totals
    the budget gates.  This is ROADMAP item 2's work-list artifact."""
    if decl is None:
        decl, _, _ = _load_decl(root)
    if cfg is None:
        cfg = _budget_config(root)
    kp, num_groups = _Geom(cfg), int(cfg["num_groups"])
    if contracts is None:
        trees = {}
        for f in CONTRACT_FILES:
            t = _parse(os.path.join(root, f))
            if t is not None:
                trees[f] = t
        contracts = _collect_contracts(trees, [])
    env = _axis_env(cfg)
    ledger = decl.get("TRANSFER_LEDGER", {})

    def size_row(row: dict) -> dict:
        out = dict(row)
        out["bytes"] = _value_bytes(row.get("value", ""), contracts, kp,
                                    num_groups, env)
        return out

    entries: dict = {}
    control: list = []
    for name, section in ledger.items():
        if name == "_control":
            control = [size_row(r) for r in section]
            continue
        entries[name] = {
            "resident": list(section.get("resident", ())),
            "up": [size_row(r) for r in section.get("up", ())],
            "down": [size_row(r) for r in section.get("down", ())],
        }
    return {
        "config": dict(cfg),
        "entries": entries,
        "control": control,
        "per_step": {
            "serial": _profile(entries.get("step_donated", {})),
            "mesh": _profile(entries.get("serve_step_donated", {})),
        },
        "provenance": {
            "dispatch_file": DISPATCH_FILE,
            "sized_by": "dragonboat_tpu/analysis/transfer.py "
                        "(capacity.bytes_for_contract)",
        },
    }


def _profile(section: dict) -> dict:
    """Per-step totals of one entry's sized rows (per_step rows only —
    masked/cached rows are off the every-step critical path)."""
    prof = {"up_bytes": 0, "down_bytes": 0,
            "up_crossings": 0, "down_crossings": 0}
    for dirn in ("up", "down"):
        for row in section.get(dirn, ()):
            if not row.get("per_step"):
                continue
            prof[f"{dirn}_crossings"] += 1
            prof[f"{dirn}_bytes"] += int(row.get("bytes") or 0)
    return prof


def emit_ledger(root: str, out_path: str | None = None) -> str:
    """Write ``build/transfer_ledger.json``; returns the path."""
    path = out_path or os.path.join(root, LEDGER_ARTIFACT)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(build_ledger(root), f, indent=2, sort_keys=True)
        f.write("\n")
    return path


# ---------------------------------------------------------------------------
# TB001: every crossing declared, every declaration real
# ---------------------------------------------------------------------------


def _entry_params(root: str, module: str, func: str
                  ) -> list[str] | None:
    tree = _parse(os.path.join(root, module))
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func:
            a = node.args
            return [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    return None


def _check_entries(findings: list[Finding], root: str, decl: dict,
                   lines: dict, qualnames: set[str]) -> None:
    ledger = decl.get("TRANSFER_LEDGER", {})
    entries = dict(decl.get("DISPATCH_ENTRIES", {}))
    line = lines.get("TRANSFER_LEDGER", 1)

    known = {
        name: (spec.get("module", ""), spec.get("function", ""))
        for name, spec in entries.items()
    }
    known.update(TELEMETRY_ENTRIES)

    for name, (module, _func) in known.items():
        if name in ledger:
            continue
        if name not in entries \
                and not os.path.exists(os.path.join(root, module)):
            continue  # fixture tree without this telemetry module
        findings.append(Finding(
            PASS, DISPATCH_FILE, line, "TB001",
            f"jit entry {name!r} has no TRANSFER_LEDGER section — "
            "its boundary crossings are undeclared"))
    for name in ledger:
        if name != "_control" and name not in known:
            findings.append(Finding(
                PASS, DISPATCH_FILE, line, "TB001",
                f"TRANSFER_LEDGER section {name!r} matches no dispatch "
                "or telemetry entry — stale declaration"))

    # array parameters must be covered: device-resident, an upload row,
    # or static jit metadata
    for name, (module, func) in known.items():
        section = ledger.get(name)
        if section is None:
            continue
        params = _entry_params(root, module, func)
        if params is None:
            continue  # module absent (fixture tree) — nothing to check
        resident = set(section.get("resident", ()))
        up_params = {row.get("param") for row in section.get("up", ())}
        for p in params:
            if p in STATIC_PARAMS or p in up_params:
                continue
            if PARAM_CLASSES.get(p) in resident:
                continue
            findings.append(Finding(
                PASS, DISPATCH_FILE, line, "TB001",
                f"entry {name!r} parameter {p!r} ({module}:{func}) is "
                "neither declared device-resident nor covered by an "
                "upload row — an undeclared host->device crossing"))
        for row in section.get("up", ()):
            bound = row.get("param")
            if bound is not None and bound not in params:
                findings.append(Finding(
                    PASS, DISPATCH_FILE, line, "TB001",
                    f"entry {name!r} upload row binds parameter "
                    f"{bound!r} which {module}:{func} does not take"))

    # every row site (and sync point) must be a real engine qualname
    for entry, _dirn, row in _ledger_rows(ledger):
        site = row.get("site", "")
        if site not in qualnames:
            findings.append(Finding(
                PASS, DISPATCH_FILE, line, "TB001",
                f"ledger row for {entry!r} names site {site!r} which "
                "matches no engine-layer function — stale declaration"))
    sp_line = lines.get("SYNC_POINTS", 1)
    ledger_tags = {row.get("tag") for _e, _d, row in _ledger_rows(ledger)}
    for qual, spec in decl.get("SYNC_POINTS", {}).items():
        if qual not in qualnames:
            findings.append(Finding(
                PASS, DISPATCH_FILE, sp_line, "TB001",
                f"SYNC_POINTS entry {qual!r} matches no engine-layer "
                "function — stale declaration"))
        if spec.get("tag") not in ledger_tags:
            findings.append(Finding(
                PASS, DISPATCH_FILE, sp_line, "TB001",
                f"SYNC_POINTS entry {qual!r} tag {spec.get('tag')!r} "
                "appears on no TRANSFER_LEDGER row — the sync's "
                "crossing is unsized"))


def _check_sizing(findings: list[Finding], lines: dict,
                  sized: dict) -> None:
    line = lines.get("TRANSFER_LEDGER", 1)
    rows = [(e, r) for e, s in sized["entries"].items()
            for d in ("up", "down") for r in s[d]]
    rows += [("_control", r) for r in sized["control"]]
    for entry, row in rows:
        if row.get("bytes") is None:
            findings.append(Finding(
                PASS, DISPATCH_FILE, line, "TB001",
                f"ledger row for {entry!r} value {row.get('value')!r} "
                "cannot be sized — not a contract class or a parseable "
                "contract string with known axes"))


# ---------------------------------------------------------------------------
# TB003: wide downloads stay masked
# ---------------------------------------------------------------------------


def _is_wide(value: str, contracts: dict) -> bool:
    """A value is wide when any field pairs the G axis with a symbolic
    kernel axis (numeric literals like the [G, 8] flag matrix are the
    deliberate narrow fetches)."""
    from dragonboat_tpu import capacity as _capacity

    def wide_axes(axes) -> bool:
        return ("G" in axes
                and any(ax in _capacity.AXIS_PARAMS for ax in axes))

    fields = contracts.get(value)
    if fields is not None:
        return any(wide_axes(fc.axes) for fc in fields.values())
    try:
        return wide_axes(parse_contract(value, "transfer").axes)
    except ContractError:
        return False


def _wide_out_fields(contracts: dict) -> frozenset:
    from dragonboat_tpu import capacity as _capacity

    return frozenset(
        fname for fname, fc in contracts.get("StepOutput", {}).items()
        if "G" in fc.axes
        and any(ax in _capacity.AXIS_PARAMS for ax in fc.axes))


def _check_masked(findings: list[Finding], decl: dict, lines: dict,
                  contracts: dict) -> None:
    line = lines.get("TRANSFER_LEDGER", 1)
    for entry, dirn, row in _ledger_rows(decl.get("TRANSFER_LEDGER", {})):
        if dirn != "down" or row.get("masked"):
            continue
        if _is_wide(row.get("value", ""), contracts):
            findings.append(Finding(
                PASS, DISPATCH_FILE, line, "TB003",
                f"ledger row for {entry!r} downloads wide value "
                f"{row.get('value')!r} unmasked — [G, axis] fetches "
                "must ride the _LazyOut masked path (declare "
                "masked=True and gate on the activity flags)"))


def _tb003_ast(findings: list[Finding], engine_trees: dict,
               sync_points: dict, contracts: dict) -> None:
    wide = _wide_out_fields(contracts)
    if not wide:
        return
    allowed = set(sync_points) | {"_LazyOut.__getitem__"}
    for relpath, tree in engine_trees.items():
        for qual, fn in _qual_funcs(tree):
            if qual in allowed:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("asarray", "array")
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in ("np", "numpy")
                        and node.args
                        and isinstance(node.args[0], ast.Attribute)
                        and node.args[0].attr in wide):
                    continue
                findings.append(Finding(
                    PASS, relpath, node.lineno, "TB003",
                    f"eager np.{node.func.attr} of wide StepOutput "
                    f"field .{node.args[0].attr} in {qual}() — the "
                    "whole [G, axis] column crosses the boundary; "
                    "fetch it through the _LazyOut masked path"))


# ---------------------------------------------------------------------------
# TB004: uploads go through staging builders
# ---------------------------------------------------------------------------


def _check_staging(findings: list[Finding], engine_trees: dict,
                   ledger_sites: set[str]) -> None:
    for relpath, tree in engine_trees.items():
        for qual, fn in _qual_funcs(tree):
            if qual in ledger_sites or qual.rsplit(".", 1)[-1] \
                    == "to_device":
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                chain = _ct._attr_chain(node.func)
                if not chain:
                    continue
                staging = (
                    (chain[-1] in ("asarray", "array")
                     and chain[0] in ("jnp",)
                     or (chain[-1] in ("asarray", "array")
                         and len(chain) >= 3 and chain[0] == "jax"
                         and chain[1] == "numpy"))
                    or (chain[-1] == "device_put"
                        and chain[0] in ("jax", "jnp"))
                )
                if staging:
                    findings.append(Finding(
                        PASS, relpath, node.lineno, "TB004",
                        f"host->device upload ({'.'.join(chain)}) in "
                        f"{qual}() which is neither a *.to_device "
                        "staging builder nor a declared "
                        "TRANSFER_LEDGER site — undeclared uploads "
                        "regrow the host hop the ledger exists to "
                        "delete"))


# ---------------------------------------------------------------------------
# TB005: syncs only at declared SYNC_POINTS (PS006, engine-wide)
# ---------------------------------------------------------------------------


def _scan_syncs(qual: str, fn: ast.FunctionDef, relpath: str
                ) -> list[Finding]:
    """The partition pass's taint walk, widened to the engine-held
    device trees and run over EVERY engine function."""
    findings: list[Finding] = []
    tainted: set[str] = set()
    seen: set[tuple[int, str]] = set()

    def is_device(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            chain = _ct._attr_chain(node)
            if len(chain) >= 2 and chain[0] == "self" \
                    and chain[1] in _SELF_ATTRS:
                return True
            return is_device(node.value)
        if isinstance(node, ast.Subscript):
            return is_device(node.value)
        if isinstance(node, ast.Call):
            c = _ct._attr_chain(node.func)
            return bool(c) and c[-1] in _DEVICE_PRODUCERS
        return False

    def emit(node: ast.AST, msg: str) -> None:
        key = (getattr(node, "lineno", 0), msg[:40])
        if key in seen:
            return
        seen.add(key)
        findings.append(Finding(
            PASS, relpath, getattr(node, "lineno", 0), "TB005",
            msg + f" in {qual}() which is not a declared SYNC_POINTS "
            "qualname — an implicit device->host sync outside the "
            "reviewed seam (declare it in engine/dispatch.py "
            "SYNC_POINTS with a METER tag, or move the read to one)"))

    def check_call(call: ast.Call) -> None:
        func = call.func
        if isinstance(func, ast.Name) \
                and func.id in ("int", "bool", "float") \
                and call.args and is_device(call.args[0]):
            emit(call, f"{func.id}() on a device value")
            return
        if not isinstance(func, ast.Attribute):
            return
        chain = _ct._attr_chain(func)
        attr = func.attr
        if attr in ("item", "tolist") and is_device(func.value):
            emit(call, f".{attr}() on a device value")
        elif attr in ("asarray", "array") and chain \
                and chain[0] in ("np", "numpy") \
                and call.args and is_device(call.args[0]):
            emit(call, f"np.{attr}() on a device value")
        elif attr == "block_until_ready":
            emit(call, ".block_until_ready()")
        elif attr == "device_get" and chain and chain[0] == "jax":
            emit(call, "jax.device_get()")

    def check_exprs(st: ast.AST) -> None:
        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                check_call(node)

    def taint(tgt: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            tainted.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                taint(el)
        elif isinstance(tgt, ast.Starred):
            taint(tgt.value)

    def visit(body: list[ast.stmt]) -> None:
        for st in body:
            if isinstance(st, (ast.If, ast.While)):
                check_exprs(st.test)
                if isinstance(st.test,
                              (ast.Name, ast.Attribute, ast.Subscript)) \
                        and is_device(st.test):
                    emit(st.test, "implicit bool() of a device value "
                                  "in a branch condition")
                visit(st.body)
                visit(st.orelse)
            elif isinstance(st, ast.For):
                check_exprs(st.iter)
                visit(st.body)
                visit(st.orelse)
            elif isinstance(st, ast.With):
                for it in st.items:
                    check_exprs(it.context_expr)
                visit(st.body)
            elif isinstance(st, ast.Try):
                visit(st.body)
                for h in st.handlers:
                    visit(h.body)
                visit(st.orelse)
                visit(st.finalbody)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(st.body)
            else:
                check_exprs(st)
                if isinstance(st, ast.Assign) and is_device(st.value):
                    for t in st.targets:
                        taint(t)
                elif isinstance(st, ast.AnnAssign) and st.value is not None \
                        and is_device(st.value):
                    taint(st.target)

    visit(fn.body)
    return findings


def _check_syncs(findings: list[Finding], engine_trees: dict,
                 sync_points: dict) -> None:
    for relpath, tree in engine_trees.items():
        for qual, fn in _qual_funcs(tree):
            if qual in sync_points:
                continue
            findings.extend(_scan_syncs(qual, fn, relpath))


# ---------------------------------------------------------------------------
# TB002 / TB006: the per-step budget gate
# ---------------------------------------------------------------------------


def _budget_config(root: str) -> dict:
    path = os.path.join(root, BUDGET_FILE)
    cfg = dict(DEFAULT_CONFIG)
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as f:
                cfg.update(json.load(f).get("config", {}))
        except (OSError, ValueError):
            pass  # the gate below reports the unreadable file
    return cfg


def _check_budget(findings: list[Finding], root: str, sized: dict,
                  default_mode: bool) -> None:
    path = os.path.join(root, BUDGET_FILE)
    relpath = BUDGET_FILE
    if not os.path.exists(path):
        if default_mode:
            findings.append(Finding(
                PASS, relpath, 1, "TB002",
                "transfer budget file missing — run scripts/lint.py "
                "--reseed-transfer-budget to seed it at the measured "
                "crossings"))
        return
    try:
        with open(path, encoding="utf-8") as f:
            budget = json.load(f).get("budget", {})
    except (OSError, ValueError):
        findings.append(Finding(
            PASS, relpath, 1, "TB002",
            "transfer budget file is unreadable JSON — re-seed it"))
        return
    for profile in ("serial", "mesh"):
        got = sized["per_step"].get(profile, {})
        lim = budget.get(profile, {})
        for key in ("up_bytes", "down_bytes"):
            limit = lim.get(f"{key}_per_step")
            if limit is not None and got.get(key, 0) > limit:
                findings.append(Finding(
                    PASS, relpath, 1, "TB002",
                    f"{profile} per-step {key.replace('_', ' ')} "
                    f"{got.get(key, 0)} exceeds budget {limit} — a "
                    "crossing grew or a new per-step row appeared; if "
                    "intended, --reseed-transfer-budget and justify in "
                    "PERF.md"))
        for key in ("up_crossings", "down_crossings"):
            limit = lim.get(f"{key}_per_step")
            if limit is not None and got.get(key, 0) > limit:
                findings.append(Finding(
                    PASS, relpath, 1, "TB006",
                    f"{profile} declares {got.get(key, 0)} per-step "
                    f"{key.replace('_', ' ')} but the budget allows "
                    f"{limit} — per-step transfer count grew; every "
                    "added crossing is a host hop on the commit path"))


# ---------------------------------------------------------------------------
# dynamic leg: METER counts vs the ledger at three geometries
# ---------------------------------------------------------------------------


def _source_key(root: str) -> str:
    import jax

    h = hashlib.sha256()
    h.update(("jax:" + getattr(jax, "__version__", "unknown")).encode())
    for f in CACHE_SOURCES:
        p = os.path.join(root, f)
        h.update(f.encode())
        if os.path.exists(p):
            with open(p, "rb") as fh:
                h.update(fh.read())
    return h.hexdigest()


def _cache_load(path: str, key: str) -> list[Finding] | None:
    try:
        with open(path, encoding="utf-8") as f:
            cache = json.load(f)
    except (OSError, ValueError):
        return None
    if cache.get("source_hash") != key:
        return None
    try:
        return [Finding(*entry) for entry in cache.get("findings", [])]
    except TypeError:
        return None


def _cache_save(path: str, key: str, findings: list[Finding]) -> None:
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({
                "source_hash": key,
                "findings": [[g.pass_name, g.path, g.line, g.rule,
                              g.message] for g in findings],
            }, f, indent=1)
    except OSError:
        pass  # cache is best-effort


def _declared_tags(decl: dict) -> set[str]:
    tags = {row.get("tag")
            for _e, _d, row in _ledger_rows(decl.get("TRANSFER_LEDGER", {}))}
    tags |= {spec.get("tag")
             for spec in decl.get("SYNC_POINTS", {}).values()}
    tags.discard(None)
    return tags


def _per_step_tag_counts(decl: dict, entry: str) -> dict:
    counts: dict = {}
    section = decl.get("TRANSFER_LEDGER", {}).get(entry, {})
    for dirn in ("up", "down"):
        for row in section.get(dirn, ()):
            if row.get("per_step"):
                tag = row.get("tag")
                counts[tag] = counts.get(tag, 0) + 1
    return counts


def _diff_counts(findings: list[Finding], geometry: str, entry: str,
                 decl: dict, counts: dict, steps: int,
                 extra_expected: dict | None = None) -> None:
    """Observed METER tags vs the ledger: exact equality for per-step
    tags, declared-tag membership for everything else."""
    declared = _declared_tags(decl)
    expected = {tag: n * steps
                for tag, n in _per_step_tag_counts(decl, entry).items()}
    expected.update(extra_expected or {})
    for tag, n in sorted(counts.items()):
        if tag not in declared:
            findings.append(Finding(
                PASS, DISPATCH_FILE, 1, "TB001",
                f"[dynamic/{geometry}] METER tag {tag!r} observed live "
                f"({n}x over {steps} steps) but declared on no "
                "TRANSFER_LEDGER row or SYNC_POINTS entry"))
    # symmetric diff: an observed-but-unexpected declared tag is a
    # count mismatch too (the ledger says 0 crossings for this entry)
    for tag in sorted(set(expected)
                      | (set(counts) & declared)):
        got, want = counts.get(tag, 0), expected.get(tag, 0)
        if got != want:
            findings.append(Finding(
                PASS, DISPATCH_FILE, 1, "TB006",
                f"[dynamic/{geometry}] tag {tag!r} crossed {got}x over "
                f"{steps} steps of entry {entry!r}; the ledger declares "
                f"exactly {want} — the static ledger and the live seam "
                "disagree"))


def live_transfer_check(root: str, decl: dict | None = None,
                        use_cache: bool = True) -> list[Finding]:
    """Run the real dispatch seams under ``capacity.METER.guard()`` at
    three geometries (serial depth-0, serial depth-1 donated, 2-device
    mesh) and diff the live METER counts against the declared ledger.
    Implicit transfers raise inside the guard; the counters prove the
    sanctioned crossings happen exactly as declared."""
    if decl is None:
        decl, _, _ = _load_decl(root)
    cache_path = os.path.join(root, CACHE_FILE)
    key = _source_key(root)
    if use_cache:
        cached = _cache_load(cache_path, key)
        if cached is not None:
            return cached
    findings = _live_impl(root, decl)
    if use_cache:
        _cache_save(cache_path, key, findings)
    return findings


def _live_impl(root: str, decl: dict) -> list[Finding]:
    import jax
    import numpy as np

    from dragonboat_tpu import capacity as _capacity
    from dragonboat_tpu.bench_loop import bench_params, make_cluster
    from dragonboat_tpu.core.kernel import output_row_flags
    from dragonboat_tpu.engine import kernel_engine as _ke
    from dragonboat_tpu.engine.dispatch import MeshDispatch, SerialDispatch

    findings: list[Finding] = []
    meter = _capacity.METER
    N = _LIVE_STEPS

    def drain(out) -> None:
        """Mirror the engine's per-step retire: the flags fetch (one
        sanctioned download) plus one masked _LazyOut field."""
        with meter.sanctioned("output_flags"):
            np.asarray(output_row_flags(out))
        _ = _ke._LazyOut(out)["s_commit"]

    # --- serial, depth 0 (non-donated oracle entry) --------------------
    kp = bench_params(3, platform="cpu")
    state = make_cluster(kp, 2, 3)
    G = int(state.term.shape[0])
    disp = SerialDispatch(kp)
    inbox = _ke._InboxBuilder(G, kp.inbox_cap, kp.msg_entries)
    inp = _ke._InputBuilder(G, kp.proposal_cap)
    state, out = disp.dispatch(state, inbox, inp, donate=False)  # warm
    np.asarray(output_row_flags(out))
    meter.reset()
    with meter.guard():
        for _ in range(N):
            state, out = disp.dispatch(state, inbox, inp, donate=False)
            drain(out)
    _diff_counts(findings, "serial-depth0", "step", decl,
                 meter.counts(), N, {"lazy_out": N})

    # --- serial, depth 1 (donated entry, retire-before-dispatch) -------
    state = make_cluster(kp, 2, 3)
    state, out = disp.dispatch(state, inbox, inp, donate=True)  # warm
    np.asarray(output_row_flags(out))
    meter.reset()
    with meter.guard():
        for _ in range(N):
            drain(out)  # retire the previous step's outputs first
            state, out = disp.dispatch(state, inbox, inp, donate=True)
    # the drain above ran on the WARM step's output too: still N drains
    _diff_counts(findings, "serial-depth1", "step_donated", decl,
                 meter.counts(), N, {"lazy_out": N})

    # --- 2-device mesh (device-resident inbox, cached cut mask) --------
    if jax.device_count() < 2:
        return findings
    from jax.sharding import Mesh

    from dragonboat_tpu.core.params import KernelParams
    from dragonboat_tpu.parallel import ici

    mkp = KernelParams(num_peers=2, log_cap=8, inbox_cap=8,
                      msg_entries=2, proposal_cap=2, readindex_cap=4)
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(1, 2), ("g", "r"))
    cluster, mstate, _box = ici.make_ici_cluster(mkp, mesh, num_groups=2)
    mdisp = MeshDispatch(cluster)
    minp = _ke._InputBuilder(cluster.total_rows, mkp.proposal_cap)
    mstate, mout = mdisp.dispatch(mstate, None, minp, donate=False)  # warm
    mdisp.pending()
    np.asarray(output_row_flags(mout))
    mdisp.set_cut(0, False)  # invalidate so cut_up restages under guard
    meter.reset()
    with meter.guard():
        for _ in range(N):
            mstate, mout = mdisp.dispatch(mstate, None, minp,
                                          donate=False)
            mdisp.pending()
            drain(mout)
    _diff_counts(findings, "mesh-2dev", "serve_step", decl,
                 meter.counts(), N, {"lazy_out": N, "cut_up": 1})
    return findings


# ---------------------------------------------------------------------------
# budget seeding
# ---------------------------------------------------------------------------


def reseed(root: str, budget_path: str | None = None,
           cfg: dict | None = None) -> dict:
    """Size the declared ledger at ``cfg`` and (re)write the budget at
    exactly the measured values; returns the new spec."""
    path = budget_path or os.path.join(root, BUDGET_FILE)
    cfg = dict(cfg or _budget_config(root))
    sized = build_ledger(root, cfg=cfg)
    spec = {
        "config": cfg,
        "budget": {
            profile: {f"{k}_per_step": v for k, v in prof.items()}
            for profile, prof in sized["per_step"].items()
        },
        "note": ("Per-step device<->host transfer budget, sized in "
                 "closed form from engine/dispatch.py TRANSFER_LEDGER "
                 "via the CONTRACTS grammar at the config geometry.  "
                 "serial = the step_donated profile, mesh = "
                 "serve_step_donated.  Update via scripts/lint.py "
                 "--reseed-transfer-budget + a PERF.md note justifying "
                 "the new crossings."),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(spec, f, indent=2, sort_keys=True)
        f.write("\n")
    return spec


# ---------------------------------------------------------------------------
# pass entry point
# ---------------------------------------------------------------------------


def run(root: str, files: list[str] | None = None,
        dynamic: bool = True) -> list[Finding]:
    default_mode = files is None
    decl, lines, findings = _load_decl(root)

    engine_trees: dict[str, ast.Module] = {}
    for p in _engine_paths(root, files):
        t = _parse(p)
        if t is not None:
            engine_trees[rel(root, p)] = t
    qualnames = {qual for tree in engine_trees.values()
                 for qual, _fn in _qual_funcs(tree)}

    contract_trees: dict[str, ast.Module] = {}
    for f in CONTRACT_FILES:
        t = _parse(os.path.join(root, f))
        if t is not None:
            contract_trees[f] = t
    if not default_mode:
        contract_trees.update(engine_trees)
    contracts = _collect_contracts(contract_trees, findings)

    _check_entries(findings, root, decl, lines, qualnames)
    _check_masked(findings, decl, lines, contracts)
    _tb003_ast(findings, engine_trees, decl.get("SYNC_POINTS", {}),
               contracts)
    ledger_sites = {row.get("site")
                    for _e, _d, row in
                    _ledger_rows(decl.get("TRANSFER_LEDGER", {}))}
    ledger_sites.discard(None)
    _check_staging(findings, engine_trees, ledger_sites)
    _check_syncs(findings, engine_trees, decl.get("SYNC_POINTS", {}))

    cfg = _budget_config(root)
    sized = build_ledger(root, decl=decl, cfg=cfg, contracts=contracts)
    _check_sizing(findings, lines, sized)
    _check_budget(findings, root, sized, default_mode)

    if default_mode and dynamic:
        findings += live_transfer_check(root, decl=decl)
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))


if __name__ == "__main__":  # pragma: no cover - CI artifact hook
    import sys

    target = emit_ledger(sys.argv[1] if len(sys.argv) > 1 else ".")
    print(f"transfer ledger written to {target}")
