"""Engine-unity pass: one step loop, one dispatch abstraction (EU001-6).

PR 6 taught the single-device engine donation + depth-1 software
pipelining; the mesh engine's bespoke ``_kernel_call`` then drifted for
three rounds (no donation, no pipelined entry, its own telemetry
wiring) before the unified ``engine/dispatch.py`` seam deleted it.
This pass is the ratchet that keeps the repo there: the engine layer
declares its dispatch contract as pure literals in
``dragonboat_tpu/engine/dispatch.py`` (``STEP_LOOP_OWNER``,
``STEP_LOOP_METHODS``, ``DISPATCH_SEAMS``, ``ENGINE_FEATURE_KNOBS``,
``ENGINE_FEATURE_CALLS``, ``DISPATCH_ENTRIES`` — parsed here with
``ast.literal_eval``, the kstate CONTRACTS idiom), and the rules hold
every engine path to it:

  EU001  second step-loop implementation: a subclass of the step-loop
         owner defines one of STEP_LOOP_METHODS (step_all,
         _stage_props, _process_outputs, ... even _kernel_call).
         Backends contribute a dispatch object via the _make_dispatch
         seam; they do not re-implement the loop.
  EU002  dispatch-feature drift: an ENGINE_FEATURE_KNOBS config
         attribute (pipeline_depth, fleet_stats_every, ...) or an
         ENGINE_FEATURE_CALLS call (the masked-output-fetch gate) is
         reachable from step_all on one concrete engine path but not
         another — or on none (dead knob).  Reachability is the
         self-call graph from step_all resolved per concrete class.
  EU003  donation parity: every DISPATCH_ENTRIES entry marked donated
         must carry donate_argnums in its defining module AND a
         kstate.DONATION declaration (composing with KC008/PS004);
         a non-donated entry must declare a waiver naming why; a
         backend may only name entries the table declares.
  EU004  pipelining parity: the owner's step_all must retire the
         carried step context BEFORE dispatching (the donation
         contract), every engine path must reach _kernel_call, and
         every dispatch backend must wire a donated entry — a backend
         without one silently degrades depth-1 to blocking dispatch.
  EU005  telemetry parity: jit/shard_map construction or a direct call
         of a dispatch entry function inside engine/ that does not
         flow through capacity.TRACKER.wrap is a retrace blind spot
         (CompileTracker never sees it); every declared entry must be
         wrapped somewhere in the engine layer.
  EU006  layering: engine/ importing an underscore-private name from
         dragonboat_tpu.core.* / dragonboat_tpu.parallel.* (or
         touching one through a module alias) bypasses the
         CONTRACTS-tagged public types the other passes check.

Pure AST — no jax import, safe in the lint fork pool.
"""

from __future__ import annotations

import ast
import glob
import os

from dragonboat_tpu.analysis.common import Finding, rel
from dragonboat_tpu.analysis.contracts import (
    _donated_entries,
    _donation_decl,
)

PASS = "engine-unity"

#: the declaration module (machine-read contract) and the engine layer
DISPATCH_FILE = "dragonboat_tpu/engine/dispatch.py"
KSTATE_FILE = "dragonboat_tpu/core/kstate.py"
ENGINE_GLOB = "dragonboat_tpu/engine/*.py"

#: --changed-only inputs: the engine layer plus every module the
#: dispatch table or the donation cross-check reads
SCOPE = (
    ENGINE_GLOB,
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/router.py",
    "dragonboat_tpu/parallel/ici.py",
)

#: module-level literals read from DISPATCH_FILE
_DECL_NAMES = (
    "STEP_LOOP_OWNER",
    "STEP_LOOP_METHODS",
    "DISPATCH_SEAMS",
    "ENGINE_FEATURE_KNOBS",
    "ENGINE_FEATURE_CALLS",
    "DISPATCH_ENTRIES",
)

#: conservative fallbacks when the declaration module is absent (a
#: fixture tree, or a catastrophically pruned checkout) — EU checks
#: still run against the owner-only core of the contract
_DECL_DEFAULTS = {
    "STEP_LOOP_OWNER": "KernelEngine",
    "STEP_LOOP_METHODS": ("step_all", "_kernel_call"),
    "DISPATCH_SEAMS": (),
    "ENGINE_FEATURE_KNOBS": (),
    "ENGINE_FEATURE_CALLS": (),
    "DISPATCH_ENTRIES": {},
}

#: extra jit entry spellings engine code must not call directly even
#: though the dispatch table does not list them (legacy serving paths)
_LEGACY_ENTRY_FNS = ("ici_serve_step", "ici_cluster_step")


def _load_decl(root: str) -> tuple[dict, dict[str, int]]:
    """The dispatch contract literals (+ their line numbers)."""
    decl = dict(_DECL_DEFAULTS)
    lines = {name: 1 for name in _DECL_NAMES}
    path = os.path.join(root, DISPATCH_FILE)
    if not os.path.exists(path):
        return decl, lines
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name not in _DECL_NAMES:
            continue
        lines[name] = node.lineno
        try:
            decl[name] = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            pass  # non-literal declaration: keep the fallback
    return decl, lines


class _Cls:
    """One class definition: name, defining file, AST node, base names."""

    def __init__(self, name: str, relpath: str, node: ast.ClassDef):
        self.name = name
        self.relpath = relpath
        self.node = node
        self.bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                self.bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                self.bases.append(b.attr)


def _classes(trees: dict[str, ast.Module]) -> dict[str, _Cls]:
    out: dict[str, _Cls] = {}
    for relpath, tree in trees.items():
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                out[node.name] = _Cls(node.name, relpath, node)
    return out


def _inherits(cls: _Cls, owner: str, classes: dict[str, _Cls]) -> bool:
    seen: set[str] = set()
    stack = list(cls.bases)
    while stack:
        b = stack.pop()
        if b == owner:
            return True
        if b in seen:
            continue
        seen.add(b)
        if b in classes:
            stack.extend(classes[b].bases)
    return False


def _mro(cls: _Cls, classes: dict[str, _Cls]) -> list[_Cls]:
    """Linearized name-based MRO over the scanned classes (left-to-right
    depth-first; good enough for the engine's single-inheritance tree)."""
    out, seen = [], set()

    def visit(c: _Cls) -> None:
        if c.name in seen:
            return
        seen.add(c.name)
        out.append(c)
        for b in c.bases:
            if b in classes:
                visit(classes[b])

    visit(cls)
    return out


def _method_table(cls: _Cls, classes: dict[str, _Cls],
                  ) -> dict[str, tuple[ast.FunctionDef, str]]:
    """Method name -> (def node, defining file), first definition wins."""
    table: dict[str, tuple[ast.FunctionDef, str]] = {}
    for c in _mro(cls, classes):
        for node in c.node.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name not in table:
                table[node.name] = (node, c.relpath)
    return table


def _self_calls(fn: ast.FunctionDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.add(node.func.attr)
    return out


def _reachable(cls: _Cls, classes: dict[str, _Cls],
               entry: str = "step_all",
               ) -> dict[str, tuple[ast.FunctionDef, str]]:
    """Methods reachable from ``entry`` via self-calls, resolved against
    THIS class's method table (the per-path view EU002/EU004 need)."""
    table = _method_table(cls, classes)
    if entry not in table:
        return {}
    seen: dict[str, tuple[ast.FunctionDef, str]] = {}
    stack = [entry]
    while stack:
        name = stack.pop()
        if name in seen or name not in table:
            continue
        seen[name] = table[name]
        stack.extend(_self_calls(table[name][0]))
    return seen


def _attr_chain(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _is_tracker_wrap(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    return len(chain) >= 2 and chain[-1] == "wrap" and "TRACKER" in chain


def _module_of(relpath_py: str) -> str:
    return relpath_py[:-3].replace("/", ".") if relpath_py.endswith(".py") \
        else relpath_py.replace("/", ".")


def _eu001(findings: list[Finding], classes: dict[str, _Cls],
           owner: str, loop_methods: tuple) -> None:
    for cls in classes.values():
        if cls.name == owner or not _inherits(cls, owner, classes):
            continue
        for node in cls.node.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in loop_methods:
                findings.append(Finding(
                    PASS, cls.relpath, node.lineno, "EU001",
                    f"second step-loop implementation: {cls.name}."
                    f"{node.name} overrides a {owner} step-loop internal "
                    "— backends contribute a dispatch object through the "
                    "_make_dispatch seam, they do not re-implement the "
                    "loop"))


def _eu002(findings: list[Finding], engines: list[_Cls],
           classes: dict[str, _Cls], knobs: tuple, calls: tuple,
           decl_lines: dict[str, int]) -> None:
    knob_readers: dict[str, set[str]] = {k: set() for k in knobs}
    call_reachers: dict[str, set[str]] = {c: set() for c in calls}
    reach = {cls.name: _reachable(cls, classes) for cls in engines}
    for cls in engines:
        for fn, _src in reach[cls.name].values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in knob_readers:
                    knob_readers[node.attr].add(cls.name)
                if isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if chain and chain[-1] in call_reachers:
                        call_reachers[chain[-1]].add(cls.name)
    analyzed = [cls for cls in engines if reach[cls.name]]
    for feature, readers in list(knob_readers.items()) \
            + list(call_reachers.items()):
        kind = "config knob" if feature in knob_readers \
            else "feature call"
        if not readers and analyzed:
            findings.append(Finding(
                PASS, DISPATCH_FILE,
                decl_lines.get("ENGINE_FEATURE_KNOBS", 1), "EU002",
                f"dead dispatch feature: {kind} {feature!r} is declared "
                "but unreachable from step_all on every engine path — "
                "delete the feature or its declaration"))
            continue
        for cls in engines:
            if cls.name in readers or not reach[cls.name]:
                continue
            findings.append(Finding(
                PASS, cls.relpath, cls.node.lineno, "EU002",
                f"dispatch-feature drift: {kind} {feature!r} gates "
                f"dispatch on {', '.join(sorted(readers))} but is "
                f"unreachable from step_all on {cls.name} — the paths "
                "have diverged"))


def _backend_classes(classes: dict[str, _Cls]) -> list[_Cls]:
    """Dispatch backends: classes defining dispatch() + self.entries."""
    out = []
    for cls in classes.values():
        has_dispatch = any(
            isinstance(n, ast.FunctionDef) and n.name == "dispatch"
            for n in cls.node.body)
        assigns_entries = any(
            isinstance(t, ast.Attribute) and t.attr == "entries"
            and isinstance(t.value, ast.Name) and t.value.id == "self"
            for n in ast.walk(cls.node) if isinstance(n, ast.Assign)
            for t in n.targets)
        if has_dispatch and assigns_entries:
            out.append(cls)
    return out


def _eu003(findings: list[Finding], root: str, entries: dict,
           backends: list[_Cls], decl_lines: dict[str, int]) -> None:
    # forward: declared entries vs their defining modules + kstate
    donation_fns: set[tuple[str, str]] | None = None
    kpath = os.path.join(root, KSTATE_FILE)
    if os.path.exists(kpath):
        with open(kpath, encoding="utf-8") as f:
            decl, _ln = _donation_decl(ast.parse(f.read(), filename=kpath))
        if decl is not None:
            donation_fns = set()
            for name, spec in decl.items():
                mod = spec.get("module", "dragonboat_tpu/core/kernel.py")
                donation_fns.add((mod, spec.get("function", name)))
    mod_donated: dict[str, dict] = {}
    for name, spec in sorted(entries.items()):
        mod = spec.get("module", "")
        fn = spec.get("function", name)
        mpath = os.path.join(root, mod)
        if mod not in mod_donated:
            if not os.path.exists(mpath):
                mod_donated[mod] = {}
            else:
                with open(mpath, encoding="utf-8") as f:
                    mod_donated[mod] = _donated_entries(
                        ast.parse(f.read(), filename=mpath))
        donated_here = mod_donated[mod]
        if spec.get("donated"):
            if os.path.exists(mpath) and fn not in donated_here:
                findings.append(Finding(
                    PASS, mod, 1, "EU003",
                    f"dispatch entry {name!r} is declared donated but "
                    f"{fn} carries no donate_argnums in {mod} — the "
                    "pipelined path would silently copy instead of "
                    "donate"))
            if donation_fns is not None and (mod, fn) not in donation_fns:
                findings.append(Finding(
                    PASS, DISPATCH_FILE,
                    decl_lines.get("DISPATCH_ENTRIES", 1), "EU003",
                    f"donated dispatch entry {name!r} ({mod}:{fn}) has "
                    "no kstate.DONATION declaration — KC008/PS004 "
                    "cannot cross-check its buffer classes"))
        else:
            if not str(spec.get("waiver", "")).strip():
                findings.append(Finding(
                    PASS, DISPATCH_FILE,
                    decl_lines.get("DISPATCH_ENTRIES", 1), "EU003",
                    f"non-donated dispatch entry {name!r} declares no "
                    "waiver — name why donation is out or donate it"))
            if fn in mod_donated[mod]:
                findings.append(Finding(
                    PASS, mod, 1, "EU003",
                    f"dispatch entry {name!r} is declared non-donated "
                    f"but {fn} carries donate_argnums in {mod} — the "
                    "table and the jit entry disagree"))
    # reverse: a backend may only name entries the table declares
    for cls in backends:
        for node in ast.walk(cls.node):
            key = None
            if isinstance(node, ast.Subscript):
                chain = _attr_chain(node.value)
                if chain and chain[-1] == "entries" \
                        and isinstance(node.slice, ast.Constant) \
                        and isinstance(node.slice.value, str):
                    key = (node.slice.value, node.lineno)
            elif isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Attribute) and t.attr == "entries"
                    for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str) \
                            and k.value not in entries:
                        findings.append(Finding(
                            PASS, cls.relpath, k.lineno, "EU003",
                            f"backend {cls.name} registers undeclared "
                            f"dispatch entry {k.value!r} — add it to "
                            "DISPATCH_ENTRIES (donated flag + waiver) "
                            "or drop it"))
                continue
            if key is not None and key[0] not in entries:
                findings.append(Finding(
                    PASS, cls.relpath, key[1], "EU003",
                    f"backend {cls.name} selects undeclared dispatch "
                    f"entry {key[0]!r} — add it to DISPATCH_ENTRIES "
                    "(donated flag + waiver) or drop it"))


def _eu004(findings: list[Finding], engines: list[_Cls],
           classes: dict[str, _Cls], owner: str, entries: dict,
           backends: list[_Cls]) -> None:
    donated_names = sorted(n for n, s in entries.items()
                           if s.get("donated"))
    # (a) the owner's step_all must retire before it dispatches
    owner_cls = classes.get(owner)
    step_all = None
    if owner_cls is not None:
        table = _method_table(owner_cls, classes)
        if "step_all" in table:
            step_all, src = table["step_all"]
    if step_all is not None:
        dispatch_lines, retire_lines, carries_ctx = [], [], False
        for node in ast.walk(step_all):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain[:1] == ["self"] and chain[-1] == "_kernel_call":
                    dispatch_lines.append(node.lineno)
                if chain[:1] == ["self"] \
                        and chain[-1] == "_process_outputs":
                    retire_lines.append(node.lineno)
            for t in (node.targets if isinstance(node, ast.Assign)
                      else []):
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Attribute) \
                            and leaf.attr == "_pending_ctx":
                        carries_ctx = True
        if dispatch_lines:
            if not carries_ctx:
                findings.append(Finding(
                    PASS, src, step_all.lineno, "EU004",
                    "step_all never carries a _pending_ctx across "
                    "steps — the depth-1 retire-before-dispatch "
                    "protocol is gone"))
            elif not retire_lines \
                    or min(retire_lines) > min(dispatch_lines):
                findings.append(Finding(
                    PASS, src, step_all.lineno, "EU004",
                    "retire-before-dispatch order broken: step_all "
                    "dispatches (_kernel_call, line "
                    f"{min(dispatch_lines)}) before retiring the "
                    "pipelined outputs (_process_outputs"
                    + (f", line {min(retire_lines)}" if retire_lines
                       else " never called")
                    + ") — donated buffers would be read after XLA "
                    "reuses them"))
    # (b) every engine path must reach the dispatch point
    for cls in engines:
        reach = _reachable(cls, classes)
        if reach and "_kernel_call" not in reach:
            findings.append(Finding(
                PASS, cls.relpath, cls.node.lineno, "EU004",
                f"engine path {cls.name} never reaches _kernel_call "
                "from step_all — the unified dispatch (and its "
                "pipelined donated entry) is unreachable on this "
                "path"))
    # (c) every backend must wire a donated entry
    for cls in backends:
        named = {node.value for node in ast.walk(cls.node)
                 if isinstance(node, ast.Constant)
                 and isinstance(node.value, str)}
        if donated_names and not named.intersection(donated_names):
            findings.append(Finding(
                PASS, cls.relpath, cls.node.lineno, "EU004",
                f"pipelining parity: backend {cls.name} references no "
                f"donated dispatch entry ({', '.join(donated_names)}) "
                "— depth-1 pipelining silently degrades to blocking "
                "non-donated dispatch on this path"))


def _eu005(findings: list[Finding], trees: dict[str, ast.Module],
           entries: dict, decl_lines: dict[str, int],
           default_mode: bool) -> None:
    entry_fns = {s.get("function", n) for n, s in entries.items()}
    entry_fns.update(_LEGACY_ENTRY_FNS)
    entry_mods = {_module_of(s.get("module", "")) for s in entries.values()}
    wrapped: set[str] = set()
    for relpath, tree in trees.items():
        # aliases of entry functions imported from the entry modules
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and (node.module in entry_mods
                         or node.module.startswith("dragonboat_tpu.core")
                         or node.module.startswith(
                             "dragonboat_tpu.parallel")):
                for a in node.names:
                    if a.name in entry_fns:
                        aliases[a.asname or a.name] = a.name

        wrap_spans: list[tuple[int, int]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_tracker_wrap(node):
                end = getattr(node, "end_lineno", node.lineno)
                wrap_spans.append((node.lineno, end))
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    wrapped.add(node.args[0].value)

        def in_wrap(node: ast.AST) -> bool:
            return any(lo <= node.lineno <= hi for lo, hi in wrap_spans)

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain and chain[-1] in ("jit", "pjit", "shard_map") \
                    and not in_wrap(node):
                findings.append(Finding(
                    PASS, relpath, node.lineno, "EU005",
                    f"{'.'.join(chain)} constructed in the engine "
                    "layer outside capacity.TRACKER.wrap — an entry "
                    "CompileTracker never sees is a retrace blind "
                    "spot; define jit entries in core/ or parallel/ "
                    "and register them in DISPATCH_ENTRIES"))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in aliases and not in_wrap(node):
                findings.append(Finding(
                    PASS, relpath, node.lineno, "EU005",
                    f"direct call of dispatch entry "
                    f"{aliases[node.func.id]!r} bypasses its "
                    "CompileTracker wrapper — compiles/retraces of "
                    "this call are invisible to the capacity model"))
    if default_mode:
        for name in sorted(entries):
            if name not in wrapped:
                findings.append(Finding(
                    PASS, DISPATCH_FILE,
                    decl_lines.get("DISPATCH_ENTRIES", 1), "EU005",
                    f"declared dispatch entry {name!r} is never "
                    "registered with capacity.TRACKER.wrap in the "
                    "engine layer — its compiles/retraces would be "
                    "invisible"))


def _eu006(findings: list[Finding],
           trees: dict[str, ast.Module]) -> None:
    private_mods = ("dragonboat_tpu.core", "dragonboat_tpu.parallel")
    for relpath, tree in trees.items():
        mod_aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith(private_mods):
                for a in node.names:
                    if a.name.startswith("_"):
                        findings.append(Finding(
                            PASS, relpath, node.lineno, "EU006",
                            f"engine layer imports kernel internal "
                            f"{a.name!r} from {node.module} — private "
                            "names bypass the CONTRACTS-tagged types "
                            "the contracts/partition passes check; "
                            "export a public seam instead"))
                    else:
                        full = f"{node.module}.{a.name}"
                        if full.startswith(private_mods):
                            mod_aliases[a.asname or a.name] = full
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith(private_mods):
                        mod_aliases[a.asname
                                    or a.name.split(".")[0]] = a.name
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in mod_aliases \
                    and node.attr.startswith("_") \
                    and not node.attr.startswith("__"):
                findings.append(Finding(
                    PASS, relpath, node.lineno, "EU006",
                    f"engine layer reaches into kernel internal "
                    f"{mod_aliases[node.value.id]}.{node.attr} — "
                    "private attributes bypass the CONTRACTS-tagged "
                    "public surface; export a public seam instead"))


def run(root: str, files: list[str] | None = None) -> list[Finding]:
    """All EU findings for the engine layer under ``root``."""
    default_mode = files is None
    if files is None:
        files = sorted(glob.glob(os.path.join(root, ENGINE_GLOB)))
    engine_prefix = os.path.join(root, "dragonboat_tpu", "engine") + os.sep
    engine_files = [p for p in files
                    if os.path.abspath(p).startswith(engine_prefix)
                    and os.path.exists(p)]

    trees: dict[str, ast.Module] = {}
    for p in engine_files:
        with open(p, encoding="utf-8") as f:
            trees[rel(root, p)] = ast.parse(f.read(), filename=p)

    decl, decl_lines = _load_decl(root)
    owner = decl["STEP_LOOP_OWNER"]
    entries = decl["DISPATCH_ENTRIES"]

    classes = _classes(trees)
    engines = [cls for cls in classes.values()
               if cls.name == owner or _inherits(cls, owner, classes)]
    backends = _backend_classes(classes)

    findings: list[Finding] = []
    _eu001(findings, classes, owner, tuple(decl["STEP_LOOP_METHODS"]))
    _eu002(findings, engines, classes,
           tuple(decl["ENGINE_FEATURE_KNOBS"]),
           tuple(decl["ENGINE_FEATURE_CALLS"]), decl_lines)
    _eu003(findings, root, entries, backends, decl_lines)
    _eu004(findings, engines, classes, owner, entries, backends)
    _eu005(findings, trees, entries, decl_lines, default_mode)
    _eu006(findings, trees)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
