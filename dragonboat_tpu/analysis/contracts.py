"""Kernel contract analyzer: shape/dtype/domain/ring-mask checking.

The batched Raft kernel's whole correctness story is that per-shard
state is fixed-width i32/bool lanes advanced in lockstep — and JAX will
happily compile a silent f32 upcast, an implicit ``[G]``→``[G,P]``
broadcast, or an unmasked ring index, corrupting every shard at once.
This pass promotes the field comments of ``core/kstate.py`` into
machine-checked contracts (the ``CONTRACTS`` literals there and in
``core/kernel.py``; grammar documented at the kstate declaration) and
verifies them two ways:

**Statically** — an abstract interpreter over the AST of
``core/kernel.py`` (reachability reuses the tracer-safety walk: every
function reachable from a jit/vmap/scan seed is analyzed).  Each value
carries an abstract ``(axes, dtype)`` where axes are SYMBOLIC names
(G/P/CAP/K/E/B/RI) resolved from ``kp.<attr>`` uses, ``.shape`` reads
and ``jnp.arange`` extents — essential because the default geometry has
K = E = B = RI = 8, so a cross-axis mixup is shape-correct and
invisible to eval_shape.  ``jnp.where`` joins branches in the lattice;
named-axis conflicts, dtype drift and un-ring-masked dynamic indices
are findings:

- KC001  implicit broadcast aligning two DIFFERENT named axes
- KC002  silent dtype conversion (f32/i32 mix, u32/i32 mix, bool
         arithmetic, int/int true division)
- KC003  comparison mixing bool and i32 operands
- KC004  dynamic index into a ring-tagged array without the
         ``& (cap - 1)`` mask (or an equivalent in-range proof:
         argmax/arange over that axis, min/clip against ``cap - 1``)
- KC005  store of a known constant outside a field's declared domain
- KC006  store whose shape/dtype contradicts the field's contract
         (``_replace`` / ``mrep`` / struct constructors / ``_set1``)

**At runtime (shapes only)** — ``init_state`` / ``empty_inbox`` /
``empty_input`` are built for a geometry with all-distinct axis sizes
and ``kernel.step`` is ``jax.eval_shape``-traced (no compile); declared
vs. actual shape/dtype diffs are KC007.  This closes the loop: the
declarations the static pass trusts are themselves checked against the
arrays the kernel really builds.

Analyzing a custom file set (``run(root, files=[...])``, used by the
fixture tests) reads ``CONTRACTS`` and domain constants from those
files and skips the runtime diff.  Parameters are bound by annotation
(``s: ShardState``) or by the repo's conventional names (``s``, ``box``,
``m``, ``inp``, ``eff``, ``pre``, ``r``, ``out``); the leading [G] axis
(and [K] for the per-message ``m``) is stripped, mirroring vmap/scan.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, replace

from dragonboat_tpu.analysis import tracer_safety as ts
from dragonboat_tpu.analysis.common import (
    FieldContract,
    Finding,
    broadcast_axes,
    parse_contract,
    rel,
)

PASS = "contracts"

KERNEL_FILE = "dragonboat_tpu/core/kernel.py"
CONTRACT_FILES = (
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/health.py",
    "dragonboat_tpu/core/invariants.py",
)
PARAMS_FILE = "dragonboat_tpu/core/params.py"

# modules whose donate_argnums decorations the KC008 cross-check scans:
# kernel.py (the default module of a DONATION entry) plus every module a
# DONATION ``module`` key may name.  scripts/lint.py folds these into
# the contracts pass's --changed-only scope.
DONATION_MODULES = (
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/parallel/ici.py",
    "dragonboat_tpu/core/router.py",
)

# KernelParams attribute -> the symbolic axis it sizes
KP_AXIS_ATTRS = {
    "num_peers": "P",
    "log_cap": "CAP",
    "inbox_cap": "K",
    "msg_entries": "E",
    "proposal_cap": "B",
    "readindex_cap": "RI",
}

# Conventional parameter names -> (contract class, axes stripped by the
# enclosing vmap/scan).  Annotations take precedence when present.
NAME_BINDINGS = {
    "s": ("ShardState", ("G",)),
    "state": ("ShardState", ("G",)),
    "box": ("Inbox", ("G",)),
    "inbox": ("Inbox", ("G",)),
    "m": ("Inbox", ("G", "K")),      # one message: the scan strips K too
    "inp": ("StepInput", ("G",)),
    "eff": ("Effects", ()),
    "pre": ("_Pre", ()),
    "r": ("_Resp", ()),
    "out": ("StepOutput", ("G",)),
}

_INT_DTYPES = ("i32", "u32")
_DTYPE_NAMES = {
    "int32": "i32", "uint32": "u32", "float32": "f32", "bool": "bool",
    "bool_": "bool", "int64": "i32", "float64": "f32",
}


@dataclass(frozen=True)
class AVal:
    """Abstract value: symbolic shape + dtype + provenance facts."""

    axes: tuple[str, ...] | None = None  # None = unknown shape
    dtype: str | None = None             # 'i32'|'u32'|'f32'|'bool'|None
    weak: bool = False                   # python-scalar weak type
    const: int | None = None             # known int value (domain checks)
    bound: str | None = None             # values proven in-range of axis
    size_axis: str | None = None         # python int == size of this axis
    maskconst: str | None = None         # python int == size(axis) - 1
    ring: str | None = None              # ring-tagged array: masked axis
    cls: str | None = None               # contract struct this value is
    strip: tuple[str, ...] = ()          # axes stripped from cls's fields
    tup: tuple | None = None             # tuple value (AVal elements)
    dt_marker: str | None = None         # value IS a dtype (I32, jnp.bool_)
    part: str | None = None              # partition: 'G' | 'rep' | None
    bcast: bool = False                  # replicated value explicitly
    #                                      broadcast to a G-shaped operand


UNKNOWN = AVal()
_KP = AVal(cls="<kp>")


def _scalar(dtype, weak=False, const=None, bound=None):
    return AVal(axes=(), dtype=dtype, weak=weak, const=const, bound=bound)


def _is_intlike(v: AVal) -> bool:
    return v.dtype in _INT_DTYPES


def _strip(axes: tuple[str, ...], strip: tuple[str, ...]) -> tuple[str, ...]:
    out = list(axes)
    for ax in strip:
        if out and out[0] == ax:
            out.pop(0)
    return tuple(out)


def _join(a: AVal, b: AVal) -> AVal:
    """Lattice join for where/sel branches.  Optimistic on unknowns."""
    if a.tup is not None and b.tup is not None and len(a.tup) == len(b.tup):
        return AVal(tup=tuple(_join(x, y) for x, y in zip(a.tup, b.tup)))
    if a.cls is not None and a.cls == b.cls:
        return a
    axes, _ = broadcast_axes(a.axes, b.axes)
    if a.dtype is None or b.dtype is None:
        dtype = a.dtype or b.dtype
    elif a.dtype == b.dtype:
        dtype = a.dtype
    elif a.weak and not b.weak:
        dtype = b.dtype
    elif b.weak and not a.weak:
        dtype = a.dtype
    else:
        dtype = None
    const = a.const if a.const == b.const else None
    bound = a.bound if a.bound == b.bound else None
    ring = a.ring if a.ring == b.ring else None
    return AVal(axes=axes, dtype=dtype, weak=a.weak and b.weak,
                const=const, bound=bound, ring=ring)


def _attr_chain(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _ann_name(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1]
    return None


class _Ctx:
    """Shared analysis context: contracts, constants, functions."""

    def __init__(self) -> None:
        self.contracts: dict[str, dict[str, FieldContract]] = {}
        self.contract_lines: dict[tuple[str, str], tuple[str, int]] = {}
        self.consts: dict[str, int] = {}
        self.funcs: dict[str, tuple[ts._Module, ast.FunctionDef]] = {}
        self.summaries: dict[str, AVal] = {}
        self.findings: list[Finding] = []

    def field(self, cls: str | None, name: str) -> FieldContract | None:
        if cls is None:
            return None
        return self.contracts.get(cls, {}).get(name)

    def domain_range(self, fc: FieldContract) -> tuple[int, int] | None:
        if fc.domain is None:
            return None
        lo, hi = self.consts.get(fc.domain[0]), self.consts.get(fc.domain[1])
        if lo is None or hi is None:
            return None
        return lo, hi


def _field_aval(ctx: _Ctx, fc: FieldContract, strip: tuple[str, ...]) -> AVal:
    axes = _strip(fc.axes, strip)
    ring = axes[0] if (fc.ring and axes) else None
    return AVal(axes=axes, dtype=fc.dtype, ring=ring)


def _struct_aval(cls: str, strip: tuple[str, ...]) -> AVal:
    return AVal(cls=cls, strip=strip)


def _collect_contracts(ctx: _Ctx, tree: ast.Module, relpath: str) -> None:
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "CONTRACTS"):
            continue
        try:
            table = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            ctx.findings.append(Finding(
                PASS, relpath, node.lineno, "KC007",
                "CONTRACTS must be a pure literal dict"))
            continue
        # remember source lines of each field key for finding anchors
        if isinstance(node.value, ast.Dict):
            for ck, cv in zip(node.value.keys, node.value.values):
                if not (isinstance(ck, ast.Constant)
                        and isinstance(cv, ast.Dict)):
                    continue
                for fk in cv.keys:
                    if isinstance(fk, ast.Constant):
                        ctx.contract_lines[(ck.value, fk.value)] = (
                            relpath, fk.lineno)
        for cls, fields in table.items():
            parsed = {}
            for fname, spec in fields.items():
                where = f"{relpath}:{cls}.{fname}"
                try:
                    parsed[fname] = parse_contract(spec, where)
                except ValueError as e:
                    path, line = ctx.contract_lines.get(
                        (cls, fname), (relpath, node.lineno))
                    ctx.findings.append(
                        Finding(PASS, path, line, "KC007", str(e)))
            ctx.contracts.setdefault(cls, {}).update(parsed)


def _collect_consts(ctx: _Ctx, tree: ast.Module) -> None:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            try:
                v = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(v, int) and not isinstance(v, bool):
                ctx.consts[node.targets[0].id] = v


# ---------------------------------------------------------------------------
# the per-function abstract interpreter
# ---------------------------------------------------------------------------

# jnp reductions: result drops the reduced axis (or all, without axis=)
_REDUCTIONS = {"sum": None, "any": "bool", "all": "bool", "min": None,
               "max": None, "prod": None, "mean": "f32"}

_INDEX_FUNCS = {
    #  name: (array argpos, index argpos, value argpos or None, row)
    "_get1": (1, 2, None, False),
    "_get_row": (1, 2, None, True),
    "_set1": (0, 1, 2, False),
    "_set_row": (0, 1, 2, True),
}


class _Interp:
    def __init__(self, ctx: _Ctx, relpath: str) -> None:
        self.ctx = ctx
        self.relpath = relpath
        self.env: dict[str, AVal] = {}
        self.returns: list[AVal] = []
        self._flagged: set[tuple[int, str]] = set()

    # -- reporting -------------------------------------------------------
    def flag(self, node: ast.AST, rule: str, msg: str) -> None:
        key = (getattr(node, "lineno", 0), rule)
        if key in self._flagged:
            return
        self._flagged.add(key)
        self.ctx.findings.append(
            Finding(PASS, self.relpath, getattr(node, "lineno", 0),
                    rule, msg))

    # -- parameter binding ----------------------------------------------
    def bind_params(self, fn: ast.FunctionDef | ast.Lambda) -> None:
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            ann = _ann_name(getattr(a, "annotation", None))
            name = a.arg
            if name == "kp" or ann == "KernelParams":
                self.env[name] = _KP
            elif ann in self.ctx.contracts:
                strip = NAME_BINDINGS.get(name, (None, ("G",)))[1]
                self.env[name] = _struct_aval(ann, strip)
            elif name in NAME_BINDINGS:
                cls, strip = NAME_BINDINGS[name]
                if cls in self.ctx.contracts:
                    self.env[name] = _struct_aval(cls, strip)
                else:
                    self.env[name] = UNKNOWN
            else:
                self.env[name] = UNKNOWN
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                self.env[extra.arg] = UNKNOWN

    # -- statements ------------------------------------------------------
    def exec_body(self, body: list[ast.stmt]) -> None:
        for st in body:
            self.exec_stmt(st)

    def exec_stmt(self, st: ast.stmt) -> None:
        if isinstance(st, ast.Assign):
            v = self.eval(st.value)
            for tgt in st.targets:
                self.assign(tgt, v)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.assign(st.target, self.eval(st.value))
        elif isinstance(st, ast.AugAssign):
            v = self.binop(st, self.eval(st.target), self.eval(st.value),
                           st.op)
            self.assign(st.target, v)
        elif isinstance(st, ast.Expr):
            self.eval(st.value)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                self.returns.append(self.eval(st.value))
        elif isinstance(st, ast.If):
            # host-level branch (trace-time static): walk both arms with
            # a shared env — a sound over-approximation for lint purposes
            self.eval(st.test)
            self.exec_body(st.body)
            self.exec_body(st.orelse)
        elif isinstance(st, ast.For):
            it = self.eval(st.iter)
            self.assign(st.target, self._loop_var(st.iter, it))
            self.exec_body(st.body)
            self.exec_body(st.orelse)
        elif isinstance(st, ast.While):
            self.eval(st.test)
            self.exec_body(st.body)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = _Interp(self.ctx, self.relpath)
            sub.env.update(self.env)
            sub.bind_params(st)
            sub._flagged = self._flagged
            sub.exec_body(st.body)
        elif isinstance(st, ast.Assert):
            self.eval(st.test)
        elif isinstance(st, ast.With):
            self.exec_body(st.body)
        elif isinstance(st, ast.Try):
            self.exec_body(st.body)
            for h in st.handlers:
                self.exec_body(h.body)
            self.exec_body(st.orelse)
            self.exec_body(st.finalbody)
        # Raise / Pass / Import / Global / Delete: nothing to track

    def _loop_var(self, iter_node: ast.AST, it: AVal) -> AVal:
        # for j in range(RI): j is an in-range index of axis RI
        if isinstance(iter_node, ast.Call) \
                and isinstance(iter_node.func, ast.Name) \
                and iter_node.func.id == "range" \
                and len(iter_node.args) == 1:
            n = self.eval(iter_node.args[0])
            if n.size_axis is not None:
                return _scalar("i32", weak=True, bound=n.size_axis)
            return _scalar("i32", weak=True)
        return UNKNOWN

    def assign(self, tgt: ast.AST, v: AVal) -> None:
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = v
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            if v.tup is not None and len(v.tup) == len(tgt.elts):
                for el, sub in zip(tgt.elts, v.tup):
                    self.assign(el, sub)
            else:
                for el in tgt.elts:
                    self.assign(el, UNKNOWN)
        elif isinstance(tgt, ast.Starred):
            self.assign(tgt.value, UNKNOWN)
        # attribute/subscript stores: no local binding

    # -- expressions -----------------------------------------------------
    def eval(self, node: ast.AST | None) -> AVal:
        if node is None:
            return UNKNOWN
        meth = getattr(self, "eval_" + type(node).__name__, None)
        if meth is not None:
            return meth(node)
        return UNKNOWN

    def eval_Constant(self, node: ast.Constant) -> AVal:
        v = node.value
        if isinstance(v, bool):
            return _scalar("bool", weak=True, const=int(v))
        if isinstance(v, int):
            return _scalar("i32", weak=True, const=v)
        if isinstance(v, float):
            return _scalar("f32", weak=True)
        return UNKNOWN

    def eval_Name(self, node: ast.Name) -> AVal:
        if node.id in self.env:
            return self.env[node.id]
        if node.id == "I32":
            return AVal(dt_marker="i32")
        if node.id == "INT_MAX":
            return _scalar("i32", weak=True)
        if node.id in ("bool", "int"):
            return AVal(dt_marker="bool" if node.id == "bool" else "i32")
        if node.id in self.ctx.consts:
            return _scalar("i32", weak=True, const=self.ctx.consts[node.id])
        return UNKNOWN

    def eval_Tuple(self, node: ast.Tuple) -> AVal:
        return AVal(tup=tuple(self.eval(e) for e in node.elts))

    eval_List = eval_Tuple

    def eval_NamedExpr(self, node: ast.NamedExpr) -> AVal:
        v = self.eval(node.value)
        self.assign(node.target, v)
        return v

    def eval_IfExp(self, node: ast.IfExp) -> AVal:
        self.eval(node.test)
        return _join(self.eval(node.body), self.eval(node.orelse))

    def eval_BoolOp(self, node: ast.BoolOp) -> AVal:
        for v in node.values:
            self.eval(v)
        return UNKNOWN

    def eval_JoinedStr(self, node: ast.JoinedStr) -> AVal:
        for v in node.values:
            self.eval(v)
        return UNKNOWN

    def eval_FormattedValue(self, node: ast.FormattedValue) -> AVal:
        self.eval(node.value)
        return UNKNOWN

    def eval_Lambda(self, node: ast.Lambda) -> AVal:
        return UNKNOWN

    def eval_Starred(self, node: ast.Starred) -> AVal:
        return self.eval(node.value)

    def eval_Attribute(self, node: ast.Attribute) -> AVal:
        # jnp.iinfo(...).max / .min: a weak scalar bound constant
        if node.attr in ("max", "min") and isinstance(node.value, ast.Call):
            base = _attr_chain(node.value.func)
            if base and base[-1] in ("iinfo", "finfo"):
                return _scalar("f32" if base[-1] == "finfo" else "i32",
                               weak=True)
        v = self.eval(node.value)
        if v is _KP or v.cls == "<kp>":
            if node.attr in KP_AXIS_ATTRS:
                return AVal(axes=(), dtype="i32", weak=True,
                            size_axis=KP_AXIS_ATTRS[node.attr])
            return _scalar("i32", weak=True)  # host config scalar/flag
        if v.cls is not None:
            fc = self.ctx.field(v.cls, node.attr)
            if fc is not None:
                return _field_aval(self.ctx, fc, v.strip)
            return UNKNOWN
        if node.attr == "shape" and v.axes is not None:
            return AVal(tup=tuple(
                AVal(axes=(), dtype="i32", weak=True, size_axis=ax)
                if ax not in ("1", "?")
                else _scalar("i32", weak=True, const=1 if ax == "1" else None)
                for ax in v.axes))
        if node.attr == "dtype" and v.dtype is not None:
            return AVal(dt_marker=v.dtype)
        if node.attr == "T" and v.axes is not None:
            return replace(v, axes=tuple(reversed(v.axes)), ring=None)
        # jnp.int32 / jnp.uint32 / jnp.bool_ as dtype markers
        chain = _attr_chain(node)
        if len(chain) >= 2 and chain[0] in ("jnp", "np", "jax", "numpy") \
                and chain[-1] in _DTYPE_NAMES:
            return AVal(dt_marker=_DTYPE_NAMES[chain[-1]])
        # module constants via an alias (P.LEADER, params.K_VOTER, ...)
        if isinstance(node.value, ast.Name) and node.attr in self.ctx.consts \
                and node.value.id not in self.env:
            return _scalar("i32", weak=True, const=self.ctx.consts[node.attr])
        return UNKNOWN

    # -- operators -------------------------------------------------------
    def _broadcast(self, node: ast.AST, a: AVal, b: AVal,
                   what: str) -> tuple[str, ...] | None:
        axes, conflict = broadcast_axes(a.axes, b.axes)
        if conflict:
            self.flag(node, "KC001",
                      f"implicit broadcast aligns distinct named axes in "
                      f"{what}: {conflict} (shapes {list(a.axes)} vs "
                      f"{list(b.axes)} — equal extents would silently "
                      "cross-wire lanes)")
        return axes

    def _dtype_of_binop(self, node: ast.AST, a: AVal, b: AVal,
                        op: ast.operator) -> str | None:
        da, db = a.dtype, b.dtype
        if da is None or db is None:
            return da or db
        strong = not (a.weak or b.weak)
        kind = type(op).__name__
        if kind in ("BitAnd", "BitOr", "BitXor"):
            if da == "bool" and db == "bool":
                return "bool"
            if "f32" in (da, db):
                self.flag(node, "KC002",
                          f"bitwise {kind} on float operand ({da}/{db})")
                return None
            if strong and ("bool" in (da, db)) and (da != db):
                self.flag(node, "KC002",
                          f"bitwise {kind} mixes bool and "
                          f"{da if db == 'bool' else db} "
                          "(mask and integer cross-wired?)")
                return None
            if strong and da != db:
                self.flag(node, "KC002",
                          f"bitwise {kind} mixes {da} and {db}")
            return da if not a.weak else db
        if kind == "Div":
            if da in _INT_DTYPES and db in _INT_DTYPES:
                self.flag(node, "KC002",
                          "int/int true division silently produces float "
                          "(use // or an explicit astype)")
                return "f32"
            return "f32"
        # Add/Sub/Mult/FloorDiv/Mod/Pow/shifts
        if kind == "Mult" and "bool" in (da, db) and (
                db in _INT_DTYPES or da in _INT_DTYPES):
            # bool * int is the kernel's masking idiom — deliberate
            return da if da in _INT_DTYPES else db
        if strong and "bool" in (da, db) and da != db:
            self.flag(node, "KC002",
                      f"{kind} arithmetic on bool and {da if db == 'bool' else db} "
                      "operands (silent upcast)")
            return None
        if strong and ("f32" in (da, db)) and (da != db):
            self.flag(node, "KC002",
                      f"{kind} mixes {da} and {db}: silent float upcast")
            return "f32"
        if strong and da in _INT_DTYPES and db in _INT_DTYPES and da != db:
            self.flag(node, "KC002",
                      f"{kind} mixes {da} and {db} (signedness drift)")
            return None
        if a.weak and not b.weak:
            return db
        return da

    def binop(self, node: ast.AST, a: AVal, b: AVal,
              op: ast.operator) -> AVal:
        axes = self._broadcast(node, a, b, "arithmetic")
        dtype = self._dtype_of_binop(node, a, b, op)
        kind = type(op).__name__
        bound = None
        # x & (size - 1): the ring-mask idiom proves in-range
        if kind == "BitAnd":
            bound = a.maskconst or b.maskconst
        # size - 1 yields a mask constant
        maskconst = None
        if kind == "Sub" and a.size_axis is not None and b.const == 1:
            maskconst = a.size_axis
        weak = a.weak and b.weak
        const = None
        if a.const is not None and b.const is not None:
            try:
                const = {
                    "Add": a.const + b.const, "Sub": a.const - b.const,
                    "Mult": a.const * b.const,
                }.get(kind)
            except Exception:
                const = None
        return AVal(axes=axes, dtype=dtype, weak=weak, const=const,
                    bound=bound, maskconst=maskconst)

    def eval_BinOp(self, node: ast.BinOp) -> AVal:
        return self.binop(node, self.eval(node.left), self.eval(node.right),
                          node.op)

    def eval_UnaryOp(self, node: ast.UnaryOp) -> AVal:
        v = self.eval(node.operand)
        if isinstance(node.op, ast.Not):
            return _scalar("bool", weak=True)
        if isinstance(node.op, ast.Invert):
            return replace(v, const=None, bound=None, maskconst=None)
        if isinstance(node.op, ast.USub):
            c = -v.const if v.const is not None else None
            return replace(v, const=c, bound=None, size_axis=None,
                           maskconst=None)
        return v

    def eval_Compare(self, node: ast.Compare) -> AVal:
        vals = [self.eval(node.left)] + [self.eval(c)
                                         for c in node.comparators]
        axes: tuple[str, ...] | None = vals[0].axes
        cur = vals[0]
        for op, nxt in zip(node.ops, vals[1:]):
            if isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)):
                cur = nxt
                continue
            axes = self._broadcast(node, replace(cur, axes=axes), nxt,
                                   "comparison")
            da, db = cur.dtype, nxt.dtype
            if da and db and not (cur.weak or nxt.weak) \
                    and ("bool" in (da, db)) and da != db:
                self.flag(node, "KC003",
                          f"comparison mixes bool and "
                          f"{da if db == 'bool' else db} operands")
            cur = nxt
        return AVal(axes=axes, dtype="bool")

    # -- subscripts ------------------------------------------------------
    def _check_ring_index(self, node: ast.AST, arr: AVal, idx: AVal,
                          via: str) -> None:
        if arr.ring is None:
            return
        if idx.dtype == "bool":
            return  # boolean masking, not positional indexing
        if idx.const is not None:
            return  # static index: in-range by construction/review
        if idx.bound == arr.ring:
            return
        self.flag(node, "KC004",
                  f"dynamic index into ring array (axis {arr.ring}) via "
                  f"{via} without the `& (cap - 1)` ring mask (or an "
                  "argmax/arange/min-against-cap-1 in-range proof) — an "
                  "unwrapped log position reads/writes the wrong slot "
                  "once the log exceeds the ring capacity")

    def _subscript_axes(self, node: ast.Subscript, base: AVal,
                        items: list[ast.AST]) -> AVal:
        if base.axes is None:
            # still ring-check a fully dynamic first index
            if items and not isinstance(items[0], ast.Slice):
                self._check_ring_index(node, base, self.eval(items[0]),
                                       "subscript")
            return UNKNOWN
        out: list[str] = []
        dim = 0
        for it in items:
            if isinstance(it, ast.Slice):
                self.eval(it.lower)
                self.eval(it.upper)
                if dim < len(base.axes):
                    out.append(base.axes[dim])
                dim += 1
            elif isinstance(it, ast.Constant) and it.value is None:
                out.append("1")
            else:
                iv = self.eval(it)
                if dim == 0:
                    self._check_ring_index(node, base, iv, "subscript")
                if iv.axes is not None and iv.axes != ():
                    out.extend(iv.axes)   # array index: its axes splice in
                dim += 1
        out.extend(base.axes[dim:])
        return AVal(axes=tuple(out), dtype=base.dtype,
                    bound=base.bound)

    def eval_Subscript(self, node: ast.Subscript) -> AVal:
        base = self.eval(node.value)
        sl = node.slice
        if base.tup is not None:
            if isinstance(sl, ast.Constant) and isinstance(sl.value, int):
                try:
                    return base.tup[sl.value]
                except IndexError:
                    return UNKNOWN
            return UNKNOWN
        if base.cls is not None or base.dt_marker is not None:
            return UNKNOWN
        items = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
        return self._subscript_axes(node, base, items)

    # -- calls -----------------------------------------------------------
    def _dtype_from_arg(self, node: ast.AST | None) -> str | None:
        if node is None:
            return None
        v = self.eval(node)
        if v.dt_marker is not None:
            return v.dt_marker
        name = _ann_name(node)
        return _DTYPE_NAMES.get(name or "", None)

    def _kwdict(self, node: ast.Call) -> dict[str, ast.AST]:
        return {k.arg: k.value for k in node.keywords if k.arg is not None}

    def _check_store(self, node: ast.AST, cls: str, fname: str,
                     v: AVal, strip: tuple[str, ...]) -> None:
        fc = self.ctx.field(cls, fname)
        if fc is None:
            if fname in ("lv", "ent_val", "prop_val", "s_ent_val"):
                return
            self.flag(node, "KC006",
                      f"store to {cls}.{fname}: field has no declared "
                      "contract (add it to CONTRACTS)")
            return
        declared = _field_aval(self.ctx, fc, strip)
        # shape: the stored value must broadcast INTO the declared shape
        if v.axes is not None and declared.axes is not None:
            axes, conflict = broadcast_axes(declared.axes, v.axes)
            if conflict or (axes != declared.axes and "?" not in axes):
                self.flag(node, "KC006",
                          f"store to {cls}.{fname}: value shape "
                          f"{list(v.axes)} does not match declared "
                          f"{list(fc.axes)} (per-shard {list(declared.axes)})")
        # dtype: strong mismatches only; weak python scalars adapt
        if v.dtype is not None and not v.weak and v.dtype != fc.dtype:
            self.flag(node, "KC006",
                      f"store to {cls}.{fname}: value dtype {v.dtype} "
                      f"contradicts declared {fc.dtype}")
        dom = self.ctx.domain_range(fc)
        if dom is not None and v.const is not None \
                and not (dom[0] <= v.const <= dom[1]):
            self.flag(node, "KC005",
                      f"store of constant {v.const} to {cls}.{fname}: "
                      f"outside declared domain "
                      f"{fc.domain[0]}..{fc.domain[1]} [{dom[0]}, {dom[1]}]")

    def _call_replace(self, node: ast.Call, target: AVal,
                      kwargs: dict[str, ast.AST]) -> AVal:
        for fname, vnode in kwargs.items():
            v = self.eval(vnode)
            if target.cls is not None and target.cls in self.ctx.contracts:
                self._check_store(node, target.cls, fname, v, target.strip)
        return target

    def _call_ctor(self, node: ast.Call, cls: str) -> AVal:
        strip = ("G",) if any(
            fc.axes[:1] == ("G",) for fc in self.ctx.contracts[cls].values()
        ) else ()
        for a in node.args:
            self.eval(a)
        for fname, vnode in self._kwdict(node).items():
            self._check_store(node, cls, fname, self.eval(vnode), strip)
        return _struct_aval(cls, strip)

    def _call_index_func(self, node: ast.Call, name: str) -> AVal:
        arr_pos, idx_pos, val_pos, row = _INDEX_FUNCS[name]
        args = node.args
        if len(args) <= max(arr_pos, idx_pos):
            return UNKNOWN
        arr = self.eval(args[arr_pos])
        idx = self.eval(args[idx_pos])
        self._check_ring_index(node, arr, idx, name)
        if val_pos is not None and len(args) > val_pos:
            v = self.eval(args[val_pos])
            # domain/dtype checks when the array is a contract field read
            src = args[arr_pos]
            if isinstance(src, ast.Attribute):
                holder = self.eval(src.value)
                fc = self.ctx.field(holder.cls, src.attr)
                if fc is not None:
                    dom = self.ctx.domain_range(fc)
                    if dom is not None and v.const is not None \
                            and not (dom[0] <= v.const <= dom[1]):
                        self.flag(node, "KC005",
                                  f"{name} stores constant {v.const} into "
                                  f"{holder.cls}.{src.attr}: outside domain "
                                  f"{fc.domain[0]}..{fc.domain[1]} "
                                  f"[{dom[0]}, {dom[1]}]")
                    if v.dtype is not None and not v.weak \
                            and v.dtype != fc.dtype:
                        self.flag(node, "KC006",
                                  f"{name} stores {v.dtype} value into "
                                  f"{holder.cls}.{src.attr} declared "
                                  f"{fc.dtype}")
            for extra in args[val_pos + 1:]:
                self.eval(extra)
            return arr
        # read form: result takes the index's shape (+ trailing row axes)
        if name == "_get_row":
            tail = arr.axes[1:] if arr.axes else None
            return AVal(axes=tail, dtype=arr.dtype)
        return AVal(axes=idx.axes, dtype=arr.dtype, bound=arr.bound)

    def _call_jnp(self, node: ast.Call, fname: str) -> AVal | None:
        args = node.args
        kw = self._kwdict(node)

        def arg(i):
            return self.eval(args[i]) if len(args) > i else UNKNOWN

        if fname == "where":
            c, a, b = arg(0), arg(1), arg(2)
            j = _join(a, b)
            axes = self._broadcast(node, replace(c, dtype=None),
                                   replace(j, dtype=None), "jnp.where")
            if a.dtype and b.dtype and not (a.weak or b.weak) \
                    and a.dtype != b.dtype \
                    and not ({a.dtype, b.dtype} <= set(_INT_DTYPES)):
                self.flag(node, "KC002",
                          f"jnp.where joins {a.dtype} and {b.dtype} "
                          "branches: silent upcast")
            return replace(j, axes=axes)
        if fname == "arange":
            n = arg(0)
            dt = self._dtype_from_arg(kw.get("dtype")) or "i32"
            if len(args) == 1 and n.size_axis is not None:
                return AVal(axes=(n.size_axis,), dtype=dt,
                            bound=n.size_axis)
            return AVal(axes=("?",), dtype=dt)
        if fname in ("zeros", "ones", "full", "empty"):
            shape = args[0] if args else None
            dt_node = kw.get("dtype")
            if fname == "full":
                dt_node = dt_node or (args[2] if len(args) > 2 else None)
                fill = arg(1)
                dt = self._dtype_from_arg(dt_node) or fill.dtype
                return AVal(axes=self._shape_from(shape), dtype=dt,
                            const=fill.const)
            dt_node = dt_node or (args[1] if len(args) > 1 else None)
            dt = self._dtype_from_arg(dt_node) or "f32"
            return AVal(axes=self._shape_from(shape), dtype=dt)
        if fname in ("zeros_like", "ones_like", "full_like", "empty_like"):
            base = arg(0)
            dt_node = kw.get("dtype")
            if fname == "full_like":
                # full_like(a, fill_value, dtype=None)
                dt_node = dt_node or (args[2] if len(args) > 2 else None)
                fill = arg(1)
                dt = self._dtype_from_arg(dt_node) or base.dtype
                return AVal(axes=base.axes, dtype=dt, const=fill.const)
            # zeros_like(a, dtype=None)
            dt_node = dt_node or (args[1] if len(args) > 1 else None)
            dt = self._dtype_from_arg(dt_node) or base.dtype
            zc = 0 if fname == "zeros_like" else 1
            return AVal(axes=base.axes, dtype=dt,
                        const=zc if fname in ("zeros_like", "ones_like")
                        else None)
        if fname in ("asarray", "array"):
            v = arg(0)
            dt = self._dtype_from_arg(
                kw.get("dtype") or (args[1] if len(args) > 1 else None))
            if dt is not None:
                return replace(v, dtype=dt, weak=False) \
                    if v.axes is not None else AVal(axes=None, dtype=dt)
            return v
        if fname == "broadcast_to":
            v, shape = arg(0), args[1] if len(args) > 1 else None
            axes = self._shape_from(shape)
            if v.axes is not None and axes is not None:
                _, conflict = broadcast_axes(axes, v.axes)
                if conflict:
                    self.flag(node, "KC001",
                              f"jnp.broadcast_to aligns distinct named "
                              f"axes: {conflict}")
            return AVal(axes=axes, dtype=v.dtype)
        if fname in ("minimum", "maximum"):
            a, b = arg(0), arg(1)
            axes = self._broadcast(node, a, b, f"jnp.{fname}")
            dt = self._dtype_of_binop(node, a, b, ast.Add())
            bound = None
            if fname == "minimum":
                # min against (size - 1), or against an already-bounded
                # value, keeps the result in range of that axis
                bound = a.maskconst or b.maskconst or a.bound or b.bound
            return AVal(axes=axes, dtype=dt, bound=bound)
        if fname == "clip":
            v = arg(0)
            hi = self.eval(kw.get("max")) if "max" in kw else arg(2)
            bound = hi.maskconst
            return replace(v, bound=bound or v.bound, const=None,
                           size_axis=None, maskconst=None, ring=None)
        if fname in _REDUCTIONS:
            v = arg(0)
            for extra in args[1:]:
                self.eval(extra)
            dt = _REDUCTIONS[fname] or v.dtype
            axis_node = kw.get("axis")
            if axis_node is None and len(args) > 1:
                axis_node = args[1]
            return self._reduce(v, axis_node, dt)
        if fname in ("argmax", "argmin"):
            v = arg(0)
            bound = None
            if v.axes is not None and len(v.axes) == 1 \
                    and v.axes[0] not in ("1", "?"):
                bound = v.axes[0]
            return _scalar("i32", bound=bound)
        if fname in ("sort", "cumsum", "flip", "roll", "abs", "sign",
                     "square"):
            v = arg(0)
            for extra in args[1:]:
                self.eval(extra)
            return replace(v, bound=None, const=None, maskconst=None,
                           ring=None)
        if fname == "expand_dims":
            v, ax = arg(0), arg(1)
            if v.axes is not None and ax.const is not None:
                lst = list(v.axes)
                pos = ax.const if ax.const >= 0 else len(lst) + 1 + ax.const
                if 0 <= pos <= len(lst):
                    lst.insert(pos, "1")
                    return AVal(axes=tuple(lst), dtype=v.dtype)
            return AVal(axes=None, dtype=v.dtype)
        if fname in ("concatenate", "stack", "hstack", "vstack"):
            for a in args:
                self.eval(a)
            return UNKNOWN
        if fname in ("int32", "uint32", "float32", "bool_"):
            v = arg(0)
            return replace(v, dtype=_DTYPE_NAMES[fname], weak=False) \
                if v.axes is not None \
                else AVal(axes=None, dtype=_DTYPE_NAMES[fname])
        if fname in ("logical_and", "logical_or", "logical_xor"):
            a, b = arg(0), arg(1)
            axes = self._broadcast(node, a, b, f"jnp.{fname}")
            return AVal(axes=axes, dtype="bool")
        if fname == "logical_not":
            v = arg(0)
            return AVal(axes=v.axes, dtype="bool")
        return None

    def _shape_from(self, node: ast.AST | None) -> tuple[str, ...] | None:
        if node is None:
            return None
        v = self.eval(node)
        if v.tup is not None:
            out = []
            for e in v.tup:
                if e.size_axis is not None:
                    out.append(e.size_axis)
                elif e.const == 1:
                    out.append("1")
                else:
                    out.append("?")
            return tuple(out)
        if v.size_axis is not None:      # scalar int shape
            return (v.size_axis,)
        if v.axes is not None and v.axes == () and v.dtype in _INT_DTYPES:
            return ("?",)
        if v.tup is None and v.axes is None:
            return None
        return None

    def _reduce(self, v: AVal, axis_node: ast.AST | None,
                dt: str | None) -> AVal:
        if axis_node is None:
            return AVal(axes=(), dtype=dt)
        ax = self.eval(axis_node)
        if v.axes is not None and ax.const is not None:
            lst = list(v.axes)
            pos = ax.const if ax.const >= 0 else len(lst) + ax.const
            if 0 <= pos < len(lst):
                lst.pop(pos)
                return AVal(axes=tuple(lst), dtype=dt)
        return AVal(axes=None, dtype=dt)

    def eval_Call(self, node: ast.Call) -> AVal:
        func = node.func

        # ----- .at[idx].set(v) chains ---------------------------------
        if isinstance(func, ast.Attribute) \
                and func.attr in ("set", "add", "multiply", "max", "min") \
                and isinstance(func.value, ast.Subscript) \
                and isinstance(func.value.value, ast.Attribute) \
                and func.value.value.attr == "at":
            base = self.eval(func.value.value.value)
            sl = func.value.slice
            items = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
            if items and not isinstance(items[0], ast.Slice):
                self._check_ring_index(node, base, self.eval(items[0]),
                                       ".at[]")
            for a in node.args:
                self.eval(a)
            return replace(base, const=None)

        # ----- method calls -------------------------------------------
        if isinstance(func, ast.Attribute):
            recv = func.value
            attr = func.attr
            chain = _attr_chain(func)
            root = chain[0] if chain else None
            if attr == "_replace":
                target = self.eval(recv)
                return self._call_replace(node, target, self._kwdict(node))
            if attr == "astype":
                v = self.eval(recv)
                dt = self._dtype_from_arg(node.args[0]) if node.args else None
                return AVal(axes=v.axes, dtype=dt or None, bound=v.bound)
            if root in ("jnp", "np", "numpy") or (
                    root == "jax" and len(chain) > 1
                    and chain[1] == "numpy"):
                res = self._call_jnp(node, attr)
                if res is not None:
                    return res
                for a in node.args:
                    self.eval(a)
                for k in node.keywords:
                    self.eval(k.value)
                return UNKNOWN
            if attr == "scan" and root in ("jax", "lax"):
                # (carry, stacked) = scan(f, init, xs): carry keeps init's
                # abstract value — the precision anchor for _shard_step
                init = self.eval(node.args[1]) if len(node.args) > 1 else \
                    self.eval(self._kwdict(node).get("init"))
                for a in node.args[2:]:
                    self.eval(a)
                return AVal(tup=(init, UNKNOWN))
            if attr in ("tree_map", "map") and root in ("jax", "tree",
                                                        "tree_util"):
                best = UNKNOWN
                for a in node.args[1:]:
                    v = self.eval(a)
                    if best is UNKNOWN and (v.cls is not None
                                            or v.axes is not None):
                        best = v
                return best
            if attr in ("fori_loop", "while_loop"):
                for a in node.args:
                    self.eval(a)
                init = self.eval(node.args[2]) if attr == "fori_loop" \
                    and len(node.args) > 2 else UNKNOWN
                return init
            if attr in _REDUCTIONS:     # x.sum(axis=..) method form
                v = self.eval(recv)
                kw = self._kwdict(node)
                axis_node = kw.get("axis") or (
                    node.args[0] if node.args else None)
                return self._reduce(v, axis_node,
                                    _REDUCTIONS[attr] or v.dtype)
            if attr == "reshape":
                self.eval(recv)
                for a in node.args:
                    self.eval(a)
                return UNKNOWN
            # unknown method: evaluate args for side-findings
            self.eval(recv)
            for a in node.args:
                self.eval(a)
            for k in node.keywords:
                self.eval(k.value)
            return UNKNOWN

        # ----- plain-name calls ---------------------------------------
        if isinstance(func, ast.Name):
            name = func.id
            if name == "sel":
                return self._call_jnp(node, "where") or UNKNOWN
            if name == "mrep":
                target = self.eval(node.args[0]) if node.args else UNKNOWN
                if len(node.args) > 1:
                    self.eval(node.args[1])
                return self._call_replace(node, target, self._kwdict(node))
            if name == "_slot" and len(node.args) == 2:
                idx = self.eval(node.args[1])
                return AVal(axes=idx.axes, dtype="i32", bound="CAP")
            if name in _INDEX_FUNCS:
                return self._call_index_func(node, name)
            if name == "onehot_select" and len(node.args) >= 3:
                oh = self.eval(node.args[0])
                arr = self.eval(node.args[1])
                return self._reduce(arr, node.args[2], arr.dtype)
            if name in self.ctx.contracts:
                return self._call_ctor(node, name)
            if name in ("range", "len", "sorted", "list", "tuple", "dict",
                        "set", "enumerate", "zip", "print", "isinstance",
                        "getattr", "hasattr", "repr", "str", "min", "max"):
                for a in node.args:
                    self.eval(a)
                return UNKNOWN
            if name in ("int", "float", "bool"):
                v = self.eval(node.args[0]) if node.args else UNKNOWN
                return _scalar({"int": "i32", "float": "f32",
                                "bool": "bool"}[name], weak=True,
                               const=v.const)
            if name in self.ctx.funcs:
                for a in node.args:
                    self.eval(a)
                for k in node.keywords:
                    self.eval(k.value)
                return self.ctx.summaries.get(name, UNKNOWN)
            for a in node.args:
                self.eval(a)
            for k in node.keywords:
                self.eval(k.value)
            return UNKNOWN

        # calling the result of a call: jax.vmap(f)(...) etc.
        self.eval(func)
        for a in node.args:
            self.eval(a)
        for k in node.keywords:
            self.eval(k.value)
        return UNKNOWN


# ---------------------------------------------------------------------------
# driving the interpreter over the jit-reachable function set
# ---------------------------------------------------------------------------


def _reachable(mods: list[ts._Module]) -> tuple[set[str], dict[str, set[str]]]:
    """Jit-reachable function names + the call graph (tracer-safety walk)."""
    global_funcs: dict[str, tuple[ts._Module, ast.FunctionDef]] = {}
    for m in mods:
        for name, fn in m.funcs.items():
            global_funcs.setdefault(name, (m, fn))
    traced: set[str] = set()
    all_calls: dict[str, set[str]] = {}
    for m in mods:
        seeds, calls = ts._seed_and_calls(m)
        traced |= seeds
        for name, callees in calls.items():
            all_calls.setdefault(name, set()).update(
                m.imports.get(c, c) for c in callees)
    frontier = list(traced)
    while frontier:
        name = frontier.pop()
        for callee in all_calls.get(name, ()):
            if callee in global_funcs and callee not in traced:
                traced.add(callee)
                frontier.append(callee)
    return traced & set(global_funcs), all_calls


def _topo_order(names: set[str], calls: dict[str, set[str]]) -> list[str]:
    """Callees before callers (cycles broken arbitrarily): summaries of
    helpers exist by the time their call sites are interpreted."""
    order: list[str] = []
    state: dict[str, int] = {}

    def visit(n: str) -> None:
        if state.get(n):            # 1 = in progress, 2 = done
            return
        state[n] = 1
        for c in sorted(calls.get(n, ())):
            if c in names and state.get(c) != 1:
                visit(c)
        state[n] = 2
        order.append(n)

    for n in sorted(names):
        visit(n)
    return order


def _summary_join(avals: list[AVal]) -> AVal:
    if not avals:
        return UNKNOWN
    out = avals[0]
    for v in avals[1:]:
        out = _join(out, v)
    return out


def _analyze(ctx: _Ctx, mods: list[ts._Module], root: str) -> None:
    reachable, calls = _reachable(mods)
    global_funcs: dict[str, tuple[ts._Module, ast.FunctionDef]] = {}
    for m in mods:
        for name, fn in m.funcs.items():
            global_funcs.setdefault(name, (m, fn))
    ctx.funcs = global_funcs
    for name in _topo_order(reachable, calls):
        mod, fn = global_funcs[name]
        interp = _Interp(ctx, rel(root, mod.path))
        interp.bind_params(fn)
        interp.exec_body(fn.body)
        ctx.summaries[name] = _summary_join(interp.returns)


# ---------------------------------------------------------------------------
# runtime cross-validation (KC007): declared vs eval-shaped reality
# ---------------------------------------------------------------------------

# all-distinct axis sizes: shape equality then implies axis-name equality
_CHECK_GEOMETRY = dict(num_peers=3, log_cap=32, inbox_cap=4, msg_entries=5,
                       proposal_cap=6, readindex_cap=16)
_CHECK_SHARDS = 7


def _dtype_name(dt) -> str:
    return _DTYPE_NAMES.get(str(dt), str(dt))


def runtime_check(kp=None, num_shards: int = _CHECK_SHARDS,
                  root: str | None = None,
                  eval_step: bool = True) -> list[Finding]:
    """Diff the declared CONTRACTS against the structures the kernel
    actually builds (init_state / empty_inbox / empty_input and the
    eval_shape of one step).  Shapes only — nothing is compiled."""
    import jax

    from dragonboat_tpu.core import kernel, kstate
    from dragonboat_tpu.core import params as kparams

    if root is None:
        root = os.getcwd()
    if kp is None:
        kp = kparams.KernelParams(**_CHECK_GEOMETRY)
    G = num_shards
    axis_env = {
        "G": G, "P": kp.num_peers, "CAP": kp.log_cap, "K": kp.inbox_cap,
        "E": kp.msg_entries, "B": kp.proposal_cap, "RI": kp.readindex_cap,
    }
    ctx = _Ctx()
    kpath = os.path.join(root, CONTRACT_FILES[0])
    for cf in CONTRACT_FILES:
        p = os.path.join(root, cf)
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            _collect_contracts(ctx, ast.parse(f.read(), filename=p),
                               rel(root, p))
    findings = list(ctx.findings)

    def anchor(cls: str, fname: str) -> tuple[str, int]:
        return ctx.contract_lines.get((cls, fname), (rel(root, kpath), 1))

    def diff(cls: str, struct) -> None:
        decl = ctx.contracts.get(cls)
        if decl is None:
            findings.append(Finding(
                PASS, rel(root, kpath), 1, "KC007",
                f"no CONTRACTS entry for {cls}"))
            return
        actual_fields = set(getattr(struct, "_fields", ()))
        for fname in sorted(actual_fields - set(decl)):
            path, line = anchor(cls, next(iter(decl), fname))
            findings.append(Finding(
                PASS, path, line, "KC007",
                f"{cls}.{fname} exists on the struct but has no declared "
                "contract"))
        for fname, fc in decl.items():
            path, line = anchor(cls, fname)
            if fname not in actual_fields:
                findings.append(Finding(
                    PASS, path, line, "KC007",
                    f"{cls}.{fname} declared but absent from the struct"))
                continue
            val = getattr(struct, fname)
            if val is None:
                if not fc.optional:
                    findings.append(Finding(
                        PASS, path, line, "KC007",
                        f"{cls}.{fname} is None but not declared optional"))
                continue
            want = tuple(axis_env.get(a, -1) for a in fc.axes)
            got = tuple(val.shape)
            if got != want:
                findings.append(Finding(
                    PASS, path, line, "KC007",
                    f"{cls}.{fname}: declared {list(fc.axes)} -> {want} "
                    f"but actual shape is {got}"))
            actual_dt = _dtype_name(val.dtype)
            if actual_dt != fc.dtype:
                findings.append(Finding(
                    PASS, path, line, "KC007",
                    f"{cls}.{fname}: declared dtype {fc.dtype} but actual "
                    f"is {actual_dt}"))

    peer_ids = list(range(1, kp.num_peers + 1))
    state = kstate.init_state(kp, G, 1, peer_ids)
    box = kstate.empty_inbox(kp, G)
    inp = kstate.empty_input(kp, G)
    diff("ShardState", state)
    diff("Inbox", box)
    diff("StepInput", inp)
    if eval_step:
        new_state, out = jax.eval_shape(
            lambda st, bx, ip: kernel.step(kp, st, bx, ip), state, box, inp)
        diff("StepOutput", out)
        diff("ShardState", new_state)

    # health structures: C/TOPK/RW are host-side constants, and k clamps
    # to G on small fleets (core/health.py) — the env mirrors that
    from dragonboat_tpu.core import health as _health

    hk = min(_health.DEFAULT_TOP_K, G)
    axis_env.update({"C": _health.NUM_CLASSES, "TOPK": hk,
                     "RW": _health.ROW_WIDTH})
    digest = _health.empty_digest(G)
    report, new_digest = jax.eval_shape(
        lambda st, bx, dg: _health._fleet_health_impl(
            st, bx, dg, k=_health.DEFAULT_TOP_K),
        state, box.from_, digest)
    diff("HealthReport", report)
    diff("HealthDigest", new_digest)
    import jax.numpy as jnp

    row = jax.eval_shape(
        _health._shard_row_impl, state, box.from_, digest,
        jax.ShapeDtypeStruct((), jnp.int32))
    diff("ShardRow", row)

    # invariant-probe structures: NI is the declared invariant count
    from dragonboat_tpu.core import invariants as _invariants

    axis_env["NI"] = _invariants.NUM_INVARIANTS
    inv_digest = _invariants.empty_digest(G)
    inv_report, new_inv_digest = jax.eval_shape(
        _invariants._check_invariants_impl, state, inv_digest)
    diff("InvariantReport", inv_report)
    diff("InvariantDigest", new_inv_digest)
    return findings


# ---------------------------------------------------------------------------
# donation contract (KC008): kstate.DONATION vs kernel.py donate_argnums
# ---------------------------------------------------------------------------


def _donation_decl(tree: ast.Module) -> tuple[dict | None, int]:
    """The DONATION literal from a kstate-shaped module (+ its line)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "DONATION":
            try:
                return ast.literal_eval(node.value), node.lineno
            except (ValueError, SyntaxError):
                return None, node.lineno
    return None, 1


def _donated_entries(tree: ast.Module) -> dict[str, tuple[tuple, list, int]]:
    """kernel.py functions carrying donate_argnums: name ->
    (argnums, positional param names, lineno)."""
    out: dict[str, tuple[tuple, list, int]] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            for k in dec.keywords:
                if k.arg != "donate_argnums":
                    continue
                try:
                    nums = ast.literal_eval(k.value)
                except (ValueError, SyntaxError):
                    nums = None
                if isinstance(nums, int):
                    nums = (nums,)
                params = [a.arg for a in (node.args.posonlyargs
                                          + node.args.args)]
                out[node.name] = (tuple(nums) if nums else (),
                                  params, node.lineno)
    return out


def donation_check(root: str, kstate_tree: ast.Module,
                   kernel_tree: ast.Module,
                   extra_trees: dict[str, ast.Module] | None = None,
                   ) -> list[Finding]:
    """Cross-check the declared donation contract against the actual
    ``donate_argnums`` decorations (both directions).

    Entries default to ``KERNEL_FILE``; an entry carrying a ``module``
    key is checked against that module instead (``extra_trees`` maps
    repo-relative module path -> parsed tree; every DONATION_MODULES
    member beyond kernel.py should be present).  An entry's ``function``
    key names the decorated function when it differs from the entry
    name."""
    findings: list[Finding] = []
    srel = rel(root, os.path.join(root, CONTRACT_FILES[0]))
    decl, decl_line = _donation_decl(kstate_tree)
    mod_trees = {KERNEL_FILE: kernel_tree}
    mod_trees.update(extra_trees or {})
    mod_entries = {m: _donated_entries(t) for m, t in mod_trees.items()}
    if decl is None:
        if any(mod_entries.values()):
            findings.append(Finding(
                PASS, srel, decl_line, "KC008",
                "jit entries donate buffers but kstate.py has no (or a "
                "non-literal) DONATION declaration"))
        return findings
    declared_fns: dict[str, set[str]] = {m: set() for m in mod_trees}
    for name, spec in decl.items():
        module = spec.get("module", KERNEL_FILE)
        fn_name = spec.get("function", name)
        mrel = rel(root, os.path.join(root, module))
        entries = mod_entries.get(module)
        if entries is None:
            findings.append(Finding(
                PASS, srel, decl_line, "KC008",
                f"DONATION entry {name} names module {module} which is "
                "not in DONATION_MODULES — the cross-check cannot see "
                "its decorators"))
            continue
        declared_fns.setdefault(module, set()).add(fn_name)
        if fn_name not in entries:
            findings.append(Finding(
                PASS, srel, decl_line, "KC008",
                f"DONATION declares {name} but {module} has no "
                f"donate_argnums-decorated function {fn_name}"))
            continue
        nums, params, line = entries[fn_name]
        want_nums = tuple(spec.get("argnums", ()))
        if nums != want_nums:
            findings.append(Finding(
                PASS, mrel, line, "KC008",
                f"{name}: donate_argnums {nums} != declared "
                f"DONATION argnums {want_nums}"))
            continue
        bound = tuple(params[i] for i in nums if i < len(params))
        want_params = tuple(spec.get("params", ()))
        if bound != want_params:
            findings.append(Finding(
                PASS, mrel, line, "KC008",
                f"{name}: donated parameters {bound} != declared "
                f"DONATION params {want_params}"))
    for module, entries in mod_entries.items():
        mrel = rel(root, os.path.join(root, module))
        for name, (_, _, line) in entries.items():
            if name not in declared_fns.get(module, set()):
                findings.append(Finding(
                    PASS, mrel, line, "KC008",
                    f"{name} donates buffers but is not declared in "
                    "kstate.DONATION — the host no-touch contract is "
                    "undocumented/unchecked"))
    return findings


# ---------------------------------------------------------------------------
# pass entry point
# ---------------------------------------------------------------------------


def run(root: str, files: list[str] | None = None) -> list[Finding]:
    default_mode = files is None
    if default_mode:
        paths = [os.path.join(root, KERNEL_FILE)]
        contract_paths = [os.path.join(root, cf) for cf in CONTRACT_FILES]
        const_paths = [os.path.join(root, PARAMS_FILE)]
    else:
        paths = list(files)
        contract_paths = list(files)
        const_paths = list(files)

    ctx = _Ctx()
    trees: dict[str, ast.Module] = {}

    def tree_of(p: str) -> ast.Module | None:
        if p not in trees:
            if not os.path.exists(p):
                return None
            with open(p, encoding="utf-8") as f:
                trees[p] = ast.parse(f.read(), filename=p)
        return trees[p]

    for p in contract_paths:
        t = tree_of(p)
        if t is not None:
            _collect_contracts(ctx, t, rel(root, p))
    for p in const_paths + paths:
        t = tree_of(p)
        if t is not None:
            _collect_consts(ctx, t)

    mods = [ts._Module(p, trees[p]) for p in paths if tree_of(p) is not None]
    _analyze(ctx, mods, root)
    findings = ctx.findings

    if default_mode:
        findings = findings + runtime_check(root=root)
        ktree = tree_of(os.path.join(root, CONTRACT_FILES[0]))
        ntree = tree_of(os.path.join(root, KERNEL_FILE))
        if ktree is not None and ntree is not None:
            extra: dict[str, ast.Module] = {}
            for m in DONATION_MODULES:
                if m == KERNEL_FILE:
                    continue
                mt = tree_of(os.path.join(root, m))
                if mt is not None:
                    extra[m] = mt
            findings = findings + donation_check(root, ktree, ntree, extra)
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))
