"""SPMD partition-safety analyzer: the G axis as a checked contract.

The mesh layout (``parallel/ici.py``) shards every kernel struct's
leading G axis flat over the ``('g', 'r')`` device mesh; the whole
scaling story rests on groups never talking to each other except through
the two declared seams (the in-mesh router exchange and the fleet-stats
reduction).  Nothing in JAX enforces that: a stray ``.sum()`` over the
batch axis, a shard_map spec that silently replicates a G-sharded
struct, or an ``int()`` on a device value in the engine step loop all
compile fine and only show up as wrong answers or a 10x serving
regression.  This pass promotes the layout to a machine-checked
discipline, driven by the ``part=``/``collective=`` tags on the
``CONTRACTS`` literals (``core/kstate.py`` grammar block):

- PS001  cross-G data flow outside a declared collective: a reduction
         whose reduced axes include G, not inside a ``jax.lax`` named
         collective over ``'g'`` and not in a function producing a
         ``collective=declared`` struct (fleet stats)
- PS002  shard_map ``in_specs``/``out_specs`` contradicting a value's
         declared partition (``part=G`` fed a replicated spec or vice
         versa, arity mismatches), plus the [dynamic] variant from the
         2-device cross-check below
- PS003  a replicated operand (named-collective result) combined with
         G-sharded data without an explicit broadcast annotation
         (``jnp.broadcast_to`` / ``jnp.expand_dims`` on the replicated
         side is the annotation)
- PS004  donation whose donor sharding differs from every result
         sharding (``kstate.DONATION`` ``donor_classes`` vs
         ``result_classes``; composes with the KC008 argnum check)
- PS005  ``pure_callback``/``io_callback``/``jax.debug.callback``
         reachable inside a shard_map body (host round-trip per device
         per step)
- PS006  implicit device→host syncs in engine hot paths: ``int()``/
         ``bool()``/``float()``/``.item()``/``.tolist()``/
         ``np.asarray`` on device values, ``block_until_ready``,
         ``jax.device_get`` inside the step_all/staging methods of
         ``kernel_engine.py``/``mesh_engine.py`` (the designated sync
         points — ``_process_outputs``, ``_device_pending``,
         ``_collect_fleet_stats`` — are exempt by design)

Static scope: the abstract interpreter (subclassing the contracts
pass's ``_Interp``) runs over ``core/fleet.py`` and ``parallel/ici.py``
— the two files that live at mesh level, where the G axis is real.
``core/kernel.py`` is deliberately NOT interpreted here: under the
engines it runs vmapped/shard_mapped with G stripped, so its per-shard
full reductions are legitimate; its structs still contribute their
``part=`` declarations.  The PS005 walk additionally descends through
kernel.py/router.py since shard_map bodies call into them.

Dynamic cross-check: the default-mode run builds a real 2-device
``('g','r')`` mesh (CPU works via
``XLA_FLAGS=--xla_force_host_platform_device_count=2``, which
scripts/lint.py sets), runs one ``ici_serve_step`` and diffs every
declared ``part=`` against the actual ``jax.sharding`` of the outputs.
Results are cached in ``.partition_cache.json`` keyed on
``jax.__version__`` + the source files, mirroring the hlo-budget pass.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from dataclasses import replace

from dragonboat_tpu.analysis import contracts as ct
from dragonboat_tpu.analysis import tracer_safety as ts
from dragonboat_tpu.analysis.common import Finding, rel

PASS = "partition"

#: mesh axis name carrying the group dimension (parallel/ici.py layout)
G_MESH_AXIS = "g"

DEFAULT_CONTRACT_FILES = (
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/fleet.py",
    "dragonboat_tpu/core/health.py",
    "dragonboat_tpu/core/invariants.py",
)
#: files interpreted at mesh level (G axis real) — see module docstring
DEFAULT_ANALYSIS_FILES = (
    "dragonboat_tpu/core/fleet.py",
    "dragonboat_tpu/core/health.py",
    "dragonboat_tpu/core/invariants.py",
    "dragonboat_tpu/parallel/ici.py",
    # the elastic controller consumes the fleet-health digest at host
    # level and must STAY jax-free: any reduction/collective appearing
    # here is a cross-G flow outside the two declared seams
    "dragonboat_tpu/control.py",
)
DEFAULT_CONST_FILES = ("dragonboat_tpu/core/params.py",)
#: PS005 walks shard_map bodies through these
DEFAULT_WALK_FILES = (
    "dragonboat_tpu/parallel/ici.py",
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/router.py",
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/fleet.py",
    "dragonboat_tpu/core/health.py",
    "dragonboat_tpu/core/invariants.py",
)
DEFAULT_ENGINE_FILES = (
    "dragonboat_tpu/engine/kernel_engine.py",
    "dragonboat_tpu/engine/mesh_engine.py",
    "dragonboat_tpu/engine/dispatch.py",
    "dragonboat_tpu/capacity.py",
)

#: every file any sub-check reads — scripts/lint.py --changed-only scope
SCOPE = tuple(dict.fromkeys(
    DEFAULT_CONTRACT_FILES + DEFAULT_ANALYSIS_FILES + DEFAULT_CONST_FILES
    + DEFAULT_WALK_FILES + DEFAULT_ENGINE_FILES))

# Conventional parameter names at MESH level: no axes are stripped (the
# G axis is present), unlike the contracts pass's vmap-level bindings.
PART_BINDINGS = {
    "s": "ShardState",
    "st": "ShardState",
    "state": "ShardState",
    "box": "Inbox",
    "bx": "Inbox",
    "inbox": "Inbox",
    "inp": "StepInput",
    "out": "StepOutput",
    "digest": "HealthDigest",
    "inv_digest": "InvariantDigest",
}

#: jax.lax named collectives — using one IS declaring cross-device flow
_NAMED_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "pbroadcast", "axis_index",
})
#: collectives whose result is identical on every participating device
_REPLICATING = frozenset({"psum", "pmean", "pmax", "pmin"})

_CALLBACKS = frozenset({"pure_callback", "io_callback", "host_callback"})

# --- PS006 scope (engine hot paths) ----------------------------------------
# Methods on the engine step/staging path where a surprise sync stalls
# every lane.  The designated sync points are exempt by design:
# _process_outputs (the one fetch per step), _device_pending (the mesh
# drain probe), _collect_fleet_stats / _fleet_inbox_from (decimated).
HOT_PATH_FUNCS = frozenset({
    "step_all", "mark_dirty", "_kernel_call", "_stage_lane",
    "_stage_props", "_prop_target", "dispatch",
})
#: self.<attr> values that live on device in both engines
_DEVICE_SELF_ATTRS = frozenset({"state", "box", "_pending_dev", "_cut_dev"})
#: calls whose results are device values
_DEVICE_PRODUCERS = frozenset({
    "kernel_step", "kernel_step_donated", "step", "step_donated",
    "ici_serve_step", "ici_cluster_step", "fleet_stats",
    "fleet_health", "shard_row",
    "jit_serve_step", "jit_serve_step_donated",
    "cluster_step", "cluster_step_donated", "dispatch",
    "output_row_flags", "to_device", "shard", "device_put", "_kernel_call",
})

# --- dynamic-check cache ---------------------------------------------------
CACHE_FILE = "dragonboat_tpu/analysis/.partition_cache.json"
CACHE_SOURCES = (
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/router.py",
    "dragonboat_tpu/core/params.py",
    "dragonboat_tpu/core/fleet.py",
    "dragonboat_tpu/core/health.py",
    "dragonboat_tpu/core/invariants.py",
    "dragonboat_tpu/parallel/ici.py",
    "dragonboat_tpu/analysis/partition.py",
)


def class_partition(ctx: ct._Ctx, cls: str | None) -> str | None:
    """The uniform declared partition of a struct, or None if mixed or
    undeclared ('G' | 'replicated')."""
    fields = ctx.contracts.get(cls or "")
    if not fields:
        return None
    parts = {fc.part for fc in fields.values() if fc.part is not None}
    return next(iter(parts)) if len(parts) == 1 else None


def _declares_collective(ctx: ct._Ctx, fn: ast.AST) -> bool:
    """Does ``fn`` construct a struct whose fields are declared
    ``collective=declared``?  Such a producer's cross-G reductions are
    the licensed seam (fleet stats)."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            fields = ctx.contracts.get(n.func.id)
            if fields and any(fc.collective == "declared"
                              for fc in fields.values()):
                return True
    return False


def _relabel_collect_findings(ctx: ct._Ctx) -> None:
    """Contract-table parse errors surface from the shared collector as
    contracts/KC007; re-own them as partition/PS000 here."""
    ctx.findings = [
        f if f.pass_name == PASS
        else Finding(PASS, f.path, f.line, "PS000", f.message)
        for f in ctx.findings
    ]


# ---------------------------------------------------------------------------
# the partition-aware abstract interpreter (PS001 / PS003)
# ---------------------------------------------------------------------------


class _PartInterp(ct._Interp):
    """Contracts interpreter with partition tracking layered on.

    Only PS* rules are emitted — the KC* checks the parent runs on the
    way through are the contracts pass's job and are dropped here."""

    def __init__(self, ctx: ct._Ctx, relpath: str) -> None:
        super().__init__(ctx, relpath)
        self._collective_depth = 0   # >0: inside a cross-G collective's args
        self._declared = False       # fn produces a collective=declared struct
        self._call_stack: list[ast.Call] = []

    # -- reporting: PS-only --------------------------------------------
    def flag(self, node: ast.AST, rule: str, msg: str) -> None:
        if not rule.startswith("PS"):
            return
        key = (getattr(node, "lineno", 0), rule)
        if key in self._flagged:
            return
        self._flagged.add(key)
        self.ctx.findings.append(
            Finding(PASS, self.relpath, getattr(node, "lineno", 0),
                    rule, msg))

    # -- parameter binding: mesh level, nothing stripped ----------------
    def bind_params(self, fn: ast.FunctionDef | ast.Lambda) -> None:
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            ann = ct._ann_name(getattr(a, "annotation", None))
            name = a.arg
            if name == "kp" or ann == "KernelParams":
                self.env[name] = ct._KP
            elif ann in self.ctx.contracts:
                self.env[name] = ct._struct_aval(ann, ())
            elif name in PART_BINDINGS \
                    and PART_BINDINGS[name] in self.ctx.contracts:
                self.env[name] = ct._struct_aval(PART_BINDINGS[name], ())
            else:
                self.env[name] = ct.UNKNOWN
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                self.env[extra.arg] = ct.UNKNOWN

    # nested defs must spawn THIS interpreter class (the parent hardcodes
    # _Interp, which would re-enable KC findings and lose partition state)
    def exec_stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = _PartInterp(self.ctx, self.relpath)
            sub.env.update(self.env)
            sub.bind_params(st)
            sub._flagged = self._flagged
            sub._call_stack = self._call_stack
            sub._declared = self._declared \
                or _declares_collective(self.ctx, st)
            sub.exec_body(st.body)
        else:
            super().exec_stmt(st)

    # -- collectives -----------------------------------------------------
    def _axis_names(self, node: ast.Call) -> set[str]:
        axis_node = None
        for k in node.keywords:
            if k.arg == "axis_name":
                axis_node = k.value
        if axis_node is None and len(node.args) > 1:
            axis_node = node.args[1]
        names: set[str] = set()
        if isinstance(axis_node, ast.Constant) \
                and isinstance(axis_node.value, str):
            names.add(axis_node.value)
        elif isinstance(axis_node, ast.Tuple):
            for e in axis_node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
        return names

    def eval_Call(self, node: ast.Call) -> ct.AVal:
        func = node.func
        cname = None
        if isinstance(func, ast.Attribute):
            chain = ct._attr_chain(func)
            if chain and chain[-1] in _NAMED_COLLECTIVES \
                    and chain[0] in ("jax", "lax"):
                cname = chain[-1]
        elif isinstance(func, ast.Name) and func.id in _NAMED_COLLECTIVES:
            cname = func.id
        # the parent takes args[2] (the body lambda) as fori_loop's carry;
        # the real init operand is args[3]
        if isinstance(func, ast.Attribute) and func.attr == "fori_loop" \
                and len(node.args) > 3:
            for a in node.args[:3]:
                self.eval(a)
            return self.eval(node.args[3])
        axes = self._axis_names(node) if cname else set()
        # a named collective over 'g' (or with unresolvable axes —
        # optimistic) licenses cross-G reductions in its operands
        suppress = cname is not None and (not axes or G_MESH_AXIS in axes)
        self._call_stack.append(node)
        if suppress:
            self._collective_depth += 1
        try:
            res = super().eval_Call(node)
            if cname in _REPLICATING and (not axes or G_MESH_AXIS in axes) \
                    and node.args:
                v0 = self.eval(node.args[0])
                base = v0 if v0.axes is not None else res
                res = replace(base, part="rep", bcast=False, cls=None,
                              tup=None, const=None, size_axis=None,
                              maskconst=None)
            return res
        finally:
            if suppress:
                self._collective_depth -= 1
            self._call_stack.pop()

    # -- PS001: reductions that erase the G axis -------------------------
    def _reduce(self, v: ct.AVal, axis_node: ast.AST | None,
                dt: str | None) -> ct.AVal:
        out = super()._reduce(v, axis_node, dt)
        reduced_g = (v.axes is not None and "G" in v.axes
                     and out.axes is not None and "G" not in out.axes)
        if reduced_g and not self._declared \
                and self._collective_depth == 0 and self._call_stack:
            self.flag(
                self._call_stack[-1], "PS001",
                "reduction erases the G (group/batch) axis outside a "
                "declared collective — at mesh level this mixes data "
                "across independent raft groups (wrap it in a jax.lax "
                "collective over 'g', or produce a collective=declared "
                "struct like FleetStats)")
        if v.part == "G" and out.axes and "G" in out.axes:
            out = replace(out, part="G")
        return out

    # -- PS003: unannotated replicated×G-sharded combination -------------
    def _broadcast(self, node: ast.AST, a: ct.AVal, b: ct.AVal,
                   what: str) -> tuple[str, ...] | None:
        for r_, g_ in ((a, b), (b, a)):
            if (r_.part == "rep" and not r_.bcast
                    and r_.axes not in (None, ())
                    and (g_.part == "G"
                         or (g_.axes is not None and "G" in g_.axes))):
                self.flag(
                    node, "PS003",
                    f"replicated collective result combined with "
                    f"G-sharded data in {what} without an explicit "
                    "broadcast annotation (jnp.broadcast_to / "
                    "jnp.expand_dims on the replicated operand makes "
                    "the fan-out intentional)")
        return super()._broadcast(node, a, b, what)

    # -- partition propagation -------------------------------------------
    def binop(self, node: ast.AST, a: ct.AVal, b: ct.AVal,
              op: ast.operator) -> ct.AVal:
        r = super().binop(node, a, b, op)
        if a.part == "G" or b.part == "G":
            r = replace(r, part="G")
        elif a.part == "rep" and b.part == "rep":
            r = replace(r, part="rep")
        return r

    def eval_Attribute(self, node: ast.Attribute) -> ct.AVal:
        v = super().eval_Attribute(node)
        recv = self.eval(node.value)
        if recv.cls is not None:
            fc = self.ctx.field(recv.cls, node.attr)
            if fc is not None and fc.part is not None:
                v = replace(v, part="G" if fc.part == "G" else "rep")
        return v

    def eval_Subscript(self, node: ast.Subscript) -> ct.AVal:
        r = super().eval_Subscript(node)
        base = self.eval(node.value)
        if base.cls is None and base.tup is None:
            if base.part == "rep":
                r = replace(r, part="rep", bcast=base.bcast)
            elif base.part == "G" and r.axes is not None and "G" in r.axes:
                r = replace(r, part="G")
        return r

    def _call_jnp(self, node: ast.Call, fname: str) -> ct.AVal | None:
        res = super()._call_jnp(node, fname)
        # broadcast_to/expand_dims IS the PS003 annotation
        if fname in ("broadcast_to", "expand_dims") and res is not None \
                and node.args:
            v = self.eval(node.args[0])
            if v.part is not None:
                res = replace(res, part=v.part, bcast=(v.part == "rep"))
        return res

    def _call_ctor(self, node: ast.Call, cls: str) -> ct.AVal:
        # mesh level: constructed structs keep their G axis
        return replace(super()._call_ctor(node, cls), strip=())


def _interpret(ctx: ct._Ctx, mods: list[ts._Module], root: str
               ) -> dict[str, list[ct.AVal]]:
    """Interpret EVERY function of the analysis modules (host helpers
    included — a stray cross-G reduce in a utility is just as wrong) and
    record per-function return avals for the PS002 out_specs check."""
    global_funcs: dict[str, tuple[ts._Module, ast.FunctionDef]] = {}
    all_calls: dict[str, set[str]] = {}
    for m in mods:
        for name, fn in m.funcs.items():
            global_funcs.setdefault(name, (m, fn))
        _, calls = ts._seed_and_calls(m)
        for name, callees in calls.items():
            all_calls.setdefault(name, set()).update(
                m.imports.get(c, c) for c in callees)
    ctx.funcs = global_funcs
    part_returns: dict[str, list[ct.AVal]] = {}
    for name in ct._topo_order(set(global_funcs), all_calls):
        mod, fn = global_funcs[name]
        interp = _PartInterp(ctx, rel(root, mod.path))
        interp._declared = _declares_collective(ctx, fn)
        interp.bind_params(fn)
        interp.exec_body(fn.body)
        ctx.summaries[name] = ct._summary_join(interp.returns)
        part_returns[name] = list(interp.returns)
    return part_returns


# ---------------------------------------------------------------------------
# PS002: shard_map specs vs declared partitions (static side)
# ---------------------------------------------------------------------------

_PS_NAMES = ("PS", "P", "PartitionSpec")


def _resolve_body(arg: ast.AST, funcs: dict) -> tuple[str | None, int]:
    """shard_map body arg -> (function name, #params pre-bound by
    functools.partial)."""
    if isinstance(arg, ast.Name):
        return (arg.id if arg.id in funcs else None), 0
    if isinstance(arg, ast.Call):
        chain = ct._attr_chain(arg.func)
        if chain and chain[-1] == "partial" and arg.args:
            inner = arg.args[0]
            if isinstance(inner, ast.Name) and inner.id in funcs:
                return inner.id, len(arg.args) - 1
    return None, 0


def _spec_axes(entry: ast.AST) -> set[str] | None:
    """One ``PS(...)`` call -> the set of mesh axis names it shards
    over, or None when unresolvable."""
    if not (isinstance(entry, ast.Call)
            and (chain := ct._attr_chain(entry.func))
            and chain[-1] in _PS_NAMES):
        return None
    names: set[str] = set()
    for a in entry.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            names.add(a.value)
        elif isinstance(a, ast.Constant) and a.value is None:
            pass
        elif isinstance(a, ast.Tuple):
            for e in a.elts:
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, str):
                    names.add(e.value)
        else:
            return None
    return names


def _spec_list(node: ast.AST) -> tuple[list[set[str]], bool] | None:
    """in_specs/out_specs value -> (per-element axis sets, was_tuple).
    Handles literal tuples, a single spec (jax broadcasts it over the
    pytree), and the ``(PS(...),) * 3`` idiom."""
    if isinstance(node, ast.Tuple):
        out = []
        for e in node.elts:
            ax = _spec_axes(e)
            if ax is None:
                return None
            out.append(ax)
        return out, True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        tup, count = node.left, node.right
        if not isinstance(tup, ast.Tuple):
            tup, count = count, tup
        if isinstance(tup, ast.Tuple) and isinstance(count, ast.Constant) \
                and isinstance(count.value, int):
            inner = _spec_list(tup)
            if inner is not None:
                return inner[0] * count.value, True
        return None
    ax = _spec_axes(node)
    if ax is not None:
        return [ax], False
    return None


def _param_partition(ctx: ct._Ctx, fn: ast.FunctionDef,
                     pname: str) -> str | None:
    for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs):
        if a.arg != pname:
            continue
        ann = ct._ann_name(getattr(a, "annotation", None))
        if ann in ctx.contracts:
            return class_partition(ctx, ann)
    cls = PART_BINDINGS.get(pname)
    if cls in ctx.contracts:
        return class_partition(ctx, cls)
    return None


def _elem_partition(ctx: ct._Ctx, el: ct.AVal) -> str | None:
    if el.cls is not None:
        return class_partition(ctx, el.cls)
    if el.part == "rep":
        return "replicated"
    if el.part == "G":
        return "G"
    return None


def _check_spec(findings: list[Finding], relpath: str, node: ast.AST,
                what: str, decl: str | None, axes: set[str]) -> None:
    if decl is None:
        return
    g_sharded = G_MESH_AXIS in axes
    if decl == "G" and not g_sharded:
        findings.append(Finding(
            PASS, relpath, node.lineno, "PS002",
            f"shard_map spec for {what} does not shard over mesh axis "
            f"'{G_MESH_AXIS}' but the value is declared part=G — every "
            "device would hold (and step) ALL groups"))
    elif decl == "replicated" and g_sharded:
        findings.append(Finding(
            PASS, relpath, node.lineno, "PS002",
            f"shard_map spec for {what} shards over mesh axis "
            f"'{G_MESH_AXIS}' but the value is declared "
            "part=replicated — each device would see a different slice "
            "of supposedly-identical data"))


def _shard_map_spec_check(ctx: ct._Ctx, mods: list[ts._Module],
                          part_returns: dict[str, list[ct.AVal]],
                          root: str) -> list[Finding]:
    findings: list[Finding] = []
    for m in mods:
        relpath = rel(root, m.path)
        for call in ast.walk(m.tree):
            if not isinstance(call, ast.Call):
                continue
            chain = ct._attr_chain(call.func)
            if not chain or chain[-1] != "shard_map" or not call.args:
                continue
            kw = {k.arg: k.value for k in call.keywords if k.arg}
            fname, skip = _resolve_body(call.args[0], m.funcs)
            if fname is None:
                continue
            fn = m.funcs[fname]
            params = [a.arg for a in
                      (fn.args.posonlyargs + fn.args.args)][skip:]
            ins = _spec_list(kw["in_specs"]) if "in_specs" in kw else None
            if ins is not None:
                specs, was_tuple = ins
                if was_tuple and len(specs) != len(params):
                    findings.append(Finding(
                        PASS, relpath, call.lineno, "PS002",
                        f"shard_map in_specs has {len(specs)} entries but "
                        f"body {fname}() takes {len(params)} (after "
                        f"{skip} partial-bound)"))
                else:
                    if not was_tuple:
                        specs = specs * len(params)
                    for pname, axes in zip(params, specs):
                        _check_spec(findings, relpath, call,
                                    f"{fname}() param {pname!r}",
                                    _param_partition(ctx, fn, pname), axes)
            outs = _spec_list(kw["out_specs"]) if "out_specs" in kw else None
            if outs is not None:
                specs, was_tuple = outs
                for ret in part_returns.get(fname, ()):
                    elems = ret.tup if ret.tup is not None else (ret,)
                    if was_tuple and ret.tup is not None \
                            and len(specs) != len(elems):
                        findings.append(Finding(
                            PASS, relpath, call.lineno, "PS002",
                            f"shard_map out_specs has {len(specs)} entries "
                            f"but body {fname}() returns {len(elems)}"))
                        continue
                    if was_tuple and ret.tup is None and len(specs) != 1:
                        continue  # structure unknown — optimistic
                    use = specs if was_tuple else list(specs) * len(elems)
                    for i, (el, axes) in enumerate(zip(elems, use)):
                        _check_spec(findings, relpath, call,
                                    f"{fname}() result[{i}]",
                                    _elem_partition(ctx, el), axes)
    return findings


# ---------------------------------------------------------------------------
# PS004: donation must preserve sharding (kstate.DONATION)
# ---------------------------------------------------------------------------


def _donation_partition_check(ctx: ct._Ctx, tree: ast.Module,
                              relpath: str) -> list[Finding]:
    decl, line = ct._donation_decl(tree)
    if not decl:
        return []
    findings: list[Finding] = []
    for name, spec in decl.items():
        donors = spec.get("donor_classes")
        results = spec.get("result_classes")
        if donors is None or results is None:
            findings.append(Finding(
                PASS, relpath, line, "PS004",
                f"DONATION entry {name!r} lacks donor_classes/"
                "result_classes — the sharding identity of the donated "
                "buffers is undeclared (XLA aliases donor memory into "
                "results; that is only sound under identical sharding)"))
            continue
        result_parts = {p for rcls in results
                        if (p := class_partition(ctx, rcls)) is not None}
        for dcls in donors:
            p = class_partition(ctx, dcls)
            if p is None:
                findings.append(Finding(
                    PASS, relpath, line, "PS004",
                    f"DONATION {name!r}: donor class {dcls} has no "
                    "uniform declared partition (tag every field part=G "
                    "or part=replicated)"))
            elif result_parts and p not in result_parts:
                findings.append(Finding(
                    PASS, relpath, line, "PS004",
                    f"DONATION {name!r}: donor {dcls} is part={p} but "
                    f"result classes are {sorted(result_parts)} — XLA "
                    "would reuse a buffer under a different sharding"))
    return findings


# ---------------------------------------------------------------------------
# PS005: host callbacks reachable inside shard_map bodies
# ---------------------------------------------------------------------------


def _callback_check(mods: list[ts._Module], root: str) -> list[Finding]:
    funcs: dict[str, tuple[ts._Module, ast.FunctionDef]] = {}
    all_calls: dict[str, set[str]] = {}
    bodies: set[str] = set()
    for m in mods:
        for name, fn in m.funcs.items():
            funcs.setdefault(name, (m, fn))
        _, calls = ts._seed_and_calls(m)
        for name, callees in calls.items():
            all_calls.setdefault(name, set()).update(
                m.imports.get(c, c) for c in callees)
        for call in ast.walk(m.tree):
            if isinstance(call, ast.Call):
                chain = ct._attr_chain(call.func)
                if chain and chain[-1] == "shard_map" and call.args:
                    fname, _ = _resolve_body(call.args[0], m.funcs)
                    if fname is not None:
                        bodies.add(fname)
    reach: set[str] = set()
    frontier = [b for b in bodies if b in funcs]
    while frontier:
        n = frontier.pop()
        if n in reach:
            continue
        reach.add(n)
        frontier.extend(c for c in all_calls.get(n, ())
                        if c in funcs and c not in reach)
    findings: list[Finding] = []
    for name in sorted(reach):
        m, fn = funcs[name]
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            chain = ct._attr_chain(call.func)
            if not chain:
                continue
            if chain[-1] in _CALLBACKS or (
                    len(chain) >= 2 and chain[-1] == "callback"
                    and chain[-2] == "debug"):
                findings.append(Finding(
                    PASS, rel(root, m.path), call.lineno, "PS005",
                    f"host callback {'.'.join(chain)} reachable inside a "
                    f"shard_map body (via {name}) — one host round-trip "
                    "per device per step serializes the mesh"))
    return findings


# ---------------------------------------------------------------------------
# PS006: implicit device→host syncs in engine hot paths
# ---------------------------------------------------------------------------


def _host_sync_check(trees: list[tuple[str, ast.Module]],
                     root: str) -> list[Finding]:
    findings: list[Finding] = []
    for path, tree in trees:
        relpath = rel(root, path)
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.FunctionDef) \
                    or fn.name not in HOT_PATH_FUNCS:
                continue
            findings.extend(_scan_hot_fn(fn, relpath))
    return findings


def _scan_hot_fn(fn: ast.FunctionDef, relpath: str) -> list[Finding]:
    findings: list[Finding] = []
    tainted: set[str] = set()
    seen: set[tuple[int, str]] = set()

    def is_device(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            chain = ct._attr_chain(node)
            if len(chain) >= 2 and chain[0] == "self" \
                    and chain[1] in _DEVICE_SELF_ATTRS:
                return True
            return is_device(node.value)
        if isinstance(node, ast.Subscript):
            return is_device(node.value)
        if isinstance(node, ast.Call):
            c = ct._attr_chain(node.func)
            return bool(c) and c[-1] in _DEVICE_PRODUCERS
        return False

    def emit(node: ast.AST, msg: str) -> None:
        key = (getattr(node, "lineno", 0), msg[:40])
        if key not in seen:
            seen.add(key)
            findings.append(Finding(
                PASS, relpath, getattr(node, "lineno", 0), "PS006",
                msg + f" in engine hot path {fn.name}() — this blocks "
                "on the device and stalls every lane (move it to a "
                "designated sync point like _process_outputs)"))

    def check_call(call: ast.Call) -> None:
        func = call.func
        if isinstance(func, ast.Name) \
                and func.id in ("int", "bool", "float") \
                and call.args and is_device(call.args[0]):
            emit(call, f"{func.id}() on a device value")
            return
        if not isinstance(func, ast.Attribute):
            return
        chain = ct._attr_chain(func)
        attr = func.attr
        if attr in ("item", "tolist") and is_device(func.value):
            emit(call, f".{attr}() on a device value")
        elif attr in ("asarray", "array") and chain \
                and chain[0] in ("np", "numpy") \
                and call.args and is_device(call.args[0]):
            emit(call, f"np.{attr}() on a device value")
        elif attr == "block_until_ready":
            emit(call, ".block_until_ready()")
        elif attr == "device_get" and chain and chain[0] == "jax":
            emit(call, "jax.device_get()")

    def check_exprs(st: ast.AST) -> None:
        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                check_call(node)

    def taint(tgt: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            tainted.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                taint(el)
        elif isinstance(tgt, ast.Starred):
            taint(tgt.value)

    def visit(body: list[ast.stmt]) -> None:
        for st in body:
            if isinstance(st, (ast.If, ast.While)):
                check_exprs(st.test)
                if isinstance(st.test,
                              (ast.Name, ast.Attribute, ast.Subscript)) \
                        and is_device(st.test):
                    emit(st.test, "implicit bool() of a device value "
                                  "in a branch condition")
                visit(st.body)
                visit(st.orelse)
            elif isinstance(st, ast.For):
                check_exprs(st.iter)
                visit(st.body)
                visit(st.orelse)
            elif isinstance(st, ast.With):
                for it in st.items:
                    check_exprs(it.context_expr)
                visit(st.body)
            elif isinstance(st, ast.Try):
                visit(st.body)
                for h in st.handlers:
                    visit(h.body)
                visit(st.orelse)
                visit(st.finalbody)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            else:
                check_exprs(st)
                if isinstance(st, ast.Assign) and is_device(st.value):
                    for t in st.targets:
                        taint(t)
                elif isinstance(st, ast.AnnAssign) and st.value is not None \
                        and is_device(st.value):
                    taint(st.target)

    visit(fn.body)
    return findings


# ---------------------------------------------------------------------------
# dynamic cross-check: declared part= vs actual jax.sharding (2 devices)
# ---------------------------------------------------------------------------


def _source_key(root: str) -> str:
    import jax

    h = hashlib.sha256()
    h.update(("jax:" + getattr(jax, "__version__", "unknown")).encode())
    for f in CACHE_SOURCES:
        p = os.path.join(root, f)
        h.update(f.encode())
        if os.path.exists(p):
            with open(p, "rb") as fh:
                h.update(fh.read())
    return h.hexdigest()


def _cache_load(path: str, key: str) -> list[Finding] | None:
    try:
        with open(path, encoding="utf-8") as f:
            cache = json.load(f)
    except (OSError, ValueError):
        return None
    if cache.get("source_hash") != key:
        return None
    try:
        return [Finding(*entry) for entry in cache.get("findings", [])]
    except TypeError:
        return None


def _cache_save(path: str, key: str, findings: list[Finding]) -> None:
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({
                "source_hash": key,
                "findings": [[g.pass_name, g.path, g.line, g.rule,
                              g.message] for g in findings],
            }, f, indent=1)
    except OSError:
        pass  # cache is best-effort


def sharding_check(root: str, parts_override: dict | None = None,
                   use_cache: bool = True) -> list[Finding]:
    """Run one real ``ici_serve_step`` on a 2-device ``('g','r')`` mesh
    and diff every declared ``part=`` tag against the actual output
    shardings.  ``parts_override`` ({(cls, field): part}) lets tests
    tamper with declarations; overridden runs bypass the cache.

    Returns [] when fewer than 2 devices are visible (scripts/lint.py
    forces 2 via XLA_FLAGS before jax initializes)."""
    import jax

    if jax.device_count() < 2:
        return []
    cache_path = os.path.join(root, CACHE_FILE)
    cacheable = parts_override is None and use_cache
    key = _source_key(root)
    if cacheable:
        cached = _cache_load(cache_path, key)
        if cached is not None:
            return cached
    findings = _sharding_check_impl(root, parts_override)
    if cacheable:
        _cache_save(cache_path, key, findings)
    return findings


def _sharding_check_impl(root: str,
                         parts_override: dict | None) -> list[Finding]:
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from dragonboat_tpu.core.params import KernelParams
    from dragonboat_tpu.parallel import ici

    ctx = ct._Ctx()
    for f in DEFAULT_CONTRACT_FILES:
        p = os.path.join(root, f)
        if os.path.exists(p):
            with open(p, encoding="utf-8") as fh:
                ct._collect_contracts(ctx, ast.parse(fh.read(), filename=p),
                                      rel(root, p))
    _relabel_collect_findings(ctx)
    if parts_override:
        for (cls, fname), part in parts_override.items():
            fc = ctx.contracts.get(cls, {}).get(fname)
            if fc is not None:
                ctx.contracts[cls][fname] = replace(fc, part=part)

    # small but legal: router.route needs inbox_cap >= 5 * (R - 1)
    kp = KernelParams(num_peers=2, log_cap=8, inbox_cap=8, msg_entries=2,
                      proposal_cap=2, readindex_cap=4)
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(1, 2), ("g", "r"))
    cluster, state, box = ici.make_ici_cluster(kp, mesh, num_groups=2)
    inp = cluster.shard(ici.self_driving_input(kp, state))
    cut = cluster.shard(
        np.zeros((cluster.total_rows, kp.num_peers), np.bool_))
    state2, box2, out = ici.ici_serve_step(
        cluster, state, box, inp, cut)

    findings = list(ctx.findings)

    def anchor(cls: str, fname: str) -> tuple[str, int]:
        return ctx.contract_lines.get(
            (cls, fname), (DEFAULT_CONTRACT_FILES[0], 1))

    for cls, struct in (("ShardState", state2), ("Inbox", box2),
                        ("StepOutput", out)):
        for fname, fc in ctx.contracts.get(cls, {}).items():
            if fc.part is None:
                continue
            val = getattr(struct, fname, None)
            if val is None:
                continue  # optional field absent under this geometry
            sh = getattr(val, "sharding", None)
            if sh is None:
                continue
            path, line = anchor(cls, fname)
            if fc.part == "G":
                split = (val.ndim > 0 and val.shape[0] > 0
                         and tuple(sh.shard_shape(val.shape))[0]
                         < val.shape[0])
                if sh.is_fully_replicated or not split:
                    findings.append(Finding(
                        PASS, path, line, "PS002",
                        f"[dynamic] {cls}.{fname} is declared part=G but "
                        "the 2-device mesh run left its leading axis "
                        "unsplit (actual sharding is "
                        f"{'replicated' if sh.is_fully_replicated else sh})"
                    ))
            elif not sh.is_fully_replicated:
                findings.append(Finding(
                    PASS, path, line, "PS002",
                    f"[dynamic] {cls}.{fname} is declared "
                    f"part=replicated but the mesh run sharded it: {sh}"))
    return findings


# ---------------------------------------------------------------------------
# pass entry point
# ---------------------------------------------------------------------------


def run(root: str, files: list[str] | None = None,
        dynamic: bool = True) -> list[Finding]:
    default_mode = files is None
    if default_mode:
        contract_paths = [os.path.join(root, f)
                          for f in DEFAULT_CONTRACT_FILES]
        const_paths = [os.path.join(root, f) for f in DEFAULT_CONST_FILES]
        analysis_paths = [os.path.join(root, f)
                          for f in DEFAULT_ANALYSIS_FILES]
        walk_paths = [os.path.join(root, f) for f in DEFAULT_WALK_FILES]
        engine_paths = [os.path.join(root, f)
                        for f in DEFAULT_ENGINE_FILES]
        donation_paths = [os.path.join(root, DEFAULT_CONTRACT_FILES[0])]
    else:
        contract_paths = const_paths = analysis_paths = walk_paths = \
            engine_paths = donation_paths = list(files)

    ctx = ct._Ctx()
    trees: dict[str, ast.Module] = {}

    def tree_of(p: str) -> ast.Module | None:
        if p not in trees:
            if not os.path.exists(p):
                return None
            with open(p, encoding="utf-8") as f:
                trees[p] = ast.parse(f.read(), filename=p)
        return trees.get(p)

    for p in contract_paths:
        t = tree_of(p)
        if t is not None:
            ct._collect_contracts(ctx, t, rel(root, p))
    _relabel_collect_findings(ctx)
    for p in const_paths + analysis_paths:
        t = tree_of(p)
        if t is not None:
            ct._collect_consts(ctx, t)

    analysis_mods = [ts._Module(p, trees[p]) for p in analysis_paths
                     if tree_of(p) is not None]
    part_returns = _interpret(ctx, analysis_mods, root)
    findings = list(ctx.findings)

    findings += _shard_map_spec_check(ctx, analysis_mods, part_returns,
                                      root)
    for p in donation_paths:
        t = tree_of(p)
        if t is not None:
            findings += _donation_partition_check(ctx, t, rel(root, p))
    walk_mods = [ts._Module(p, trees[p]) for p in walk_paths
                 if tree_of(p) is not None]
    findings += _callback_check(walk_mods, root)
    findings += _host_sync_check(
        [(p, trees[p]) for p in engine_paths if tree_of(p) is not None],
        root)
    if default_mode and dynamic:
        findings += sharding_check(root)
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))
