"""Concurrency lint: ``# guarded-by: <lock>`` discipline.

Scope: classes that own a ``threading.Lock`` / ``RLock`` / ``Condition``
attribute (assigned in ``__init__``) in the modules shared across
threads.  Two rules:

- CC001  a mutable container attribute (dict/list/set/deque display or
         constructor) of a lock-owning class carries no trailing
         ``# guarded-by: <name>`` annotation on its ``__init__``
         assignment.  ``# guarded-by: <init-only>`` declares an
         attribute immutable after construction.
- CC002  a guarded attribute is mutated (assignment, augmented
         assignment, subscript store/delete, or a mutating method call
         such as ``.append`` / ``.pop`` / ``.clear``) outside a ``with
         self.<lock>:`` block in a method other than ``__init__``.
         ``with self._locks[i]:`` counts as holding ``_locks`` — the
         key-sharded book pattern (request.py PendingProposal).
         ``init-only`` attributes admit no post-``__init__`` mutation
         at all.
- CC003  static deadlock detection: a lock-order graph is built per
         class with an edge A -> B whenever ``self.B`` is acquired
         (directly, or transitively through a same-class method call)
         while ``self.A`` is held.  A cycle in that graph — including
         the length-1 cycle of re-acquiring a non-reentrant
         Lock/Semaphore — means two threads interleaving those paths
         can deadlock, and is flagged at one acquisition site per
         cycle edge.

Known limitations (documented, on purpose): mutations through a local
alias (``q = self.queues[a]; q.append(...)``) are not tracked — the
lint enforces the annotation discipline at the ``self.<attr>`` access
level, which is where review happens.  The lock-order graph is
likewise per-class and ``self.``-scoped: an inversion spanning two
objects' locks (hub holding its mu while calling into a pool that
grabs its own) needs runtime lock profiling, not this lint.
"""

from __future__ import annotations

import ast
import os
import re

from dragonboat_tpu.analysis.common import Finding, rel

PASS = "concurrency"

DEFAULT_MODULES = (
    "dragonboat_tpu/transport/hub.py",
    "dragonboat_tpu/engine/apply_pool.py",
    "dragonboat_tpu/request.py",
    "dragonboat_tpu/events.py",
    "dragonboat_tpu/chaos/crashfs.py",
    "dragonboat_tpu/telemetry.py",
    "dragonboat_tpu/flight.py",
    "dragonboat_tpu/lifecycle.py",
    "dragonboat_tpu/core/health.py",
    "dragonboat_tpu/capacity.py",
    "dragonboat_tpu/fabric.py",
    "dragonboat_tpu/transport/chan.py",
    "dragonboat_tpu/transport/tcp.py",
    # the fleet controller: lockless BY CONTRACT (all state advances
    # under the NodeHost tick, never from transport threads) — listed so
    # the day it grows a lock, its streak/cooldown dicts must declare
    # their guard like every other shared book
    "dragonboat_tpu/control.py",
)

LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                        "BoundedSemaphore"})
MUTABLE_CTORS = frozenset({"dict", "list", "set", "deque", "defaultdict",
                           "OrderedDict", "Counter", "bytearray"})
MUTATING_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "update", "add",
    "setdefault", "sort", "reverse", "rotate",
})

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_<][A-Za-z0-9_\->]*)")

INIT_ONLY = "<init-only>"

# acquiring one of these twice on the same thread is safe
REENTRANT_CTORS = frozenset({"RLock", "Condition"})


def _ctor_name(node: ast.AST) -> str | None:
    """`threading.Lock()` -> "Lock"; `deque()` -> "deque"."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _lock_kind(node: ast.AST) -> str | None:
    """The lock ctor name when ``node`` builds a lock (or lock array)."""
    name = _ctor_name(node)
    if name in LOCK_CTORS:
        return name
    # [threading.Lock() for _ in range(n)] — a lock *array*
    if isinstance(node, ast.ListComp):
        name = _ctor_name(node.elt)
        if name in LOCK_CTORS:
            return name
    if isinstance(node, (ast.List, ast.Tuple)) and node.elts and all(
            _ctor_name(e) in LOCK_CTORS for e in node.elts):
        return _ctor_name(node.elts[0])
    return None


def _is_lock_value(node: ast.AST) -> bool:
    return _lock_kind(node) is not None


def _is_mutable_value(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    return _ctor_name(node) in MUTABLE_CTORS


def _self_attr(node: ast.AST) -> str | None:
    """The attribute name when ``node`` is exactly ``self.<attr>``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _self_attr_base(node: ast.AST) -> str | None:
    """`self.x`, `self.x[i]`, `self.x[i][j]` -> "x"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return _self_attr(node)


class _ClassInfo:
    def __init__(self, cls: ast.ClassDef, src_lines: list[str]) -> None:
        self.cls = cls
        self.locks: set[str] = set()
        self.lock_kinds: dict[str, str] = {}  # attr -> ctor name
        self.guards: dict[str, str] = {}   # attr -> lock name / INIT_ONLY
        self.mutable_unannotated: list[tuple[str, int]] = []
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return
        for node in ast.walk(init):
            targets: list[ast.AST] = []
            value: ast.AST | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                kind = _lock_kind(value)
                if kind is not None:
                    self.locks.add(attr)
                    self.lock_kinds[attr] = kind
                    continue
                m = _GUARD_RE.search(src_lines[node.lineno - 1])
                if m:
                    self.guards[attr] = m.group(1)
                elif _is_mutable_value(value):
                    self.mutable_unannotated.append((attr, node.lineno))


class _MethodChecker(ast.NodeVisitor):
    """Flag guarded-attr mutations outside their lock's with-block."""

    def __init__(self, info: _ClassInfo, relpath: str,
                 findings: list[Finding]) -> None:
        self.info = info
        self.relpath = relpath
        self.findings = findings
        self.held: list[str] = []       # lock-attr names currently held

    def _flag(self, node: ast.AST, attr: str) -> None:
        guard = self.info.guards[attr]
        if guard == INIT_ONLY:
            msg = (f"`self.{attr}` is declared init-only but mutated "
                   f"after __init__")
        else:
            msg = (f"mutation of `self.{attr}` outside `with "
                   f"self.{guard}:` (declared guarded-by: {guard})")
        self.findings.append(Finding(PASS, self.relpath, node.lineno,
                                     "CC002", msg))

    def _check_target(self, node: ast.AST, stmt: ast.AST) -> None:
        attr = _self_attr_base(node)
        if attr is None or attr not in self.info.guards:
            return
        guard = self.info.guards[attr]
        if guard == INIT_ONLY or guard not in self.held:
            self._flag(stmt, attr)

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            attr = _self_attr_base(item.context_expr)
            if attr is not None and attr in self.info.locks:
                acquired.append(attr)
                self.held.append(attr)
        self.generic_visit(node)
        for a in acquired:
            self.held.remove(a)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            for el in (tgt.elts if isinstance(tgt, (ast.Tuple, ast.List))
                       else [tgt]):
                self._check_target(el, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_target(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._check_target(tgt, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
            self._check_target(f.value, node)
        self.generic_visit(node)


class _LockOrderVisitor(ast.NodeVisitor):
    """Per-method acquisition structure for the CC003 lock-order graph.

    Collects (a) direct lock acquisitions with the held-stack at that
    point, and (b) same-class method calls with the held-stack at the
    call site; ``_lock_order_edges`` closes (b) over each callee's
    transitive acquisition set.
    """

    def __init__(self, locks: set[str]) -> None:
        self.locks = locks
        self.held: list[str] = []
        # lock acquired -> (held locks at acquisition, lineno)
        self.acquisitions: list[tuple[str, tuple[str, ...], int]] = []
        # same-class method called -> (held locks at call, lineno)
        self.calls: list[tuple[str, tuple[str, ...], int]] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            attr = _self_attr_base(item.context_expr)
            if attr is not None and attr in self.locks:
                self.acquisitions.append(
                    (attr, tuple(self.held), item.context_expr.lineno))
                acquired.append(attr)
                self.held.append(attr)
        self.generic_visit(node)
        for a in acquired:
            self.held.remove(a)

    def visit_Call(self, node: ast.Call) -> None:
        meth = _self_attr(node.func)
        if meth is not None:
            self.calls.append((meth, tuple(self.held), node.lineno))
        self.generic_visit(node)


def _lock_order_edges(info: _ClassInfo
                      ) -> dict[tuple[str, str], tuple[int, str]]:
    """Edges ``(held, acquired) -> (lineno, via)`` for one class."""
    methods = {n.name: n for n in info.cls.body
               if isinstance(n, ast.FunctionDef)}
    visits = {}
    for name, fn in methods.items():
        v = _LockOrderVisitor(info.locks)
        for st in fn.body:
            v.visit(st)
        visits[name] = v
    # transitive closure: every lock a method can acquire, including
    # through same-class calls (cycle-tolerant fixpoint)
    acquires = {name: {a for a, _, _ in v.acquisitions}
                for name, v in visits.items()}
    changed = True
    while changed:
        changed = False
        for name, v in visits.items():
            for callee, _, _ in v.calls:
                if callee in acquires and not (
                        acquires[callee] <= acquires[name]):
                    acquires[name] |= acquires[callee]
                    changed = True
    edges: dict[tuple[str, str], tuple[int, str]] = {}
    for name, v in visits.items():
        for lock, held, line in v.acquisitions:
            for h in held:
                edges.setdefault((h, lock), (line, name))
        for callee, held, line in v.calls:
            if not held or callee not in acquires:
                continue
            for lock in acquires[callee]:
                for h in held:
                    edges.setdefault(
                        (h, lock), (line, f"{name} -> self.{callee}()"))
    return edges


def _find_cycle(nodes: set[str], edges: set[tuple[str, str]]
                ) -> list[str] | None:
    """One directed cycle as [a, b, ..., a], or None."""
    succ: dict[str, list[str]] = {n: [] for n in nodes}
    for a, b in edges:
        succ.setdefault(a, []).append(b)
    state: dict[str, int] = {}
    stack: list[str] = []

    def dfs(n: str) -> list[str] | None:
        state[n] = 1
        stack.append(n)
        for m in sorted(succ.get(n, ())):
            if state.get(m, 0) == 1:
                return stack[stack.index(m):] + [m]
            if state.get(m, 0) == 0:
                cyc = dfs(m)
                if cyc is not None:
                    return cyc
        stack.pop()
        state[n] = 2
        return None

    for n in sorted(nodes):
        if state.get(n, 0) == 0:
            cyc = dfs(n)
            if cyc is not None:
                return cyc
    return None


def _check_lock_order(cls: ast.ClassDef, info: _ClassInfo, relpath: str,
                      findings: list[Finding]) -> None:
    edges = _lock_order_edges(info)
    # self-edge on a non-reentrant lock: one thread deadlocks itself
    for (a, b), (line, via) in sorted(edges.items()):
        if a == b and info.lock_kinds.get(a) not in REENTRANT_CTORS:
            findings.append(Finding(
                PASS, relpath, line, "CC003",
                f"{cls.name}: `self.{a}` "
                f"({info.lock_kinds.get(a, 'Lock')}) re-acquired while "
                f"already held (via {via}) — non-reentrant, deadlocks "
                "the acquiring thread"))
    proper = {(a, b) for (a, b) in edges if a != b}
    cyc = _find_cycle({n for e in proper for n in e}, proper)
    if cyc is not None:
        sites = "; ".join(
            f"{a}->{b} at line {edges[(a, b)][0]} ({edges[(a, b)][1]})"
            for a, b in zip(cyc, cyc[1:]))
        findings.append(Finding(
            PASS, relpath, edges[(cyc[0], cyc[1])][0], "CC003",
            f"{cls.name}: lock-order cycle "
            f"{' -> '.join('self.' + n for n in cyc)} — two threads "
            f"interleaving these paths deadlock ({sites})"))


def _check_class(cls: ast.ClassDef, info: _ClassInfo, relpath: str,
                 findings: list[Finding]) -> None:
    if not info.locks:
        return                          # not a lock-owning class
    for attr, line in info.mutable_unannotated:
        findings.append(Finding(
            PASS, relpath, line, "CC001",
            f"mutable attribute `self.{attr}` of lock-owning class "
            f"{cls.name} has no `# guarded-by:` annotation"))
    for node in cls.body:
        if not isinstance(node, ast.FunctionDef) or node.name == "__init__":
            continue
        _MethodChecker(info, relpath, findings).visit(node)
    _check_lock_order(cls, info, relpath, findings)


def run(root: str, files: list[str] | None = None) -> list[Finding]:
    paths = files if files is not None else [
        os.path.join(root, m) for m in DEFAULT_MODULES]
    findings: list[Finding] = []
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=p)
        lines = src.splitlines()
        classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
        infos = {c.name: _ClassInfo(c, lines) for c in classes}
        # single-module inheritance: a book subclassing _ClockedBook owns
        # its base's lock and inherits its guard declarations
        for c in classes:
            seen, stack = {c.name}, [b.id for b in c.bases
                                     if isinstance(b, ast.Name)]
            while stack:
                base = stack.pop()
                if base in seen or base not in infos:
                    continue
                seen.add(base)
                infos[c.name].locks |= infos[base].locks
                for attr, k in infos[base].lock_kinds.items():
                    infos[c.name].lock_kinds.setdefault(attr, k)
                for attr, g in infos[base].guards.items():
                    infos[c.name].guards.setdefault(attr, g)
                stack.extend(b.id for b in infos[base].cls.bases
                             if isinstance(b, ast.Name))
        for c in classes:
            _check_class(c, infos[c.name], rel(root, p), findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
