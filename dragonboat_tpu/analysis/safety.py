"""Raft safety verifier: invariant contracts checked against the kernel.

The seventh analysis pass closes the loop the other six leave open: the
contracts pass proves SHAPE discipline, the partition pass proves
PLACEMENT discipline — neither says anything about whether a
shape-correct, well-placed store is allowed by the Raft *protocol*.
This pass consumes the machine-readable ``core/kstate.py INVARIANTS``
declarations (grammar: ``analysis/common.parse_invariant``) three ways:

**Declaration lint** — every invariant must parse, and every field it
references (``field`` / ``prev.field`` / ``quorum(field)`` terms) must
be a declared ``ShardState`` contract field (RS001); a missing or empty
``INVARIANTS`` table is itself a finding (RS006) — the runtime probe
and the model checker silently become vacuous without it.

**Store obligations** — an AST provenance analysis over
``core/kernel.py``: for each store (``mrep`` / ``_replace`` keyword) to
an invariant-participating field, the store's value and mask
expressions are resolved transitively through local definitions, and
the store must *provably preserve* the invariant or be flagged:

- RS002  a store to ``committed`` that is neither monotone in
         ``s.committed`` (the ``jnp.maximum(s.committed, ...)``
         follower form) nor derived from ``_sorted_match_quorum_index``
         under a leader-role + current-term mask — the
         ``leader_commit_quorum`` / ``commit_monotone`` obligations
- RS003  the RequestVote handler grants without persisting the
         candidate id into ``vote`` — the ``vote_once_per_term``
         obligation (a granted-but-unrecorded vote lets a second
         same-term candidate win a disjoint quorum)
- RS004  a store that can LOWER ``last`` (truncation) whose mask does
         not derive from a comparison against ``s.committed`` — the
         ``commit_within_log`` obligation (a replicate must never
         truncate the committed prefix)

**Model-check gate** — the fast small-scope exhaustive run of
``scripts/model_check.py`` (the real jitted kernel as transition
relation) must report zero violations (RS005).  Like the hlo-budget
and partition dynamic checks, the result is cached in
``analysis/.safety_cache.json`` keyed by a hash of the participating
sources + the jax version, so the ~10 s exploration only re-runs when
the kernel (or the checker itself) actually changed.

Custom file sets (``run(root, files=[...])``, fixture tests) run the
declaration lint + store obligations over those files and skip the
model-check gate; ``run(root, dynamic=False)`` skips only the gate.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os

from dragonboat_tpu.analysis.common import (
    Finding,
    InvariantError,
    parse_contracts,
    parse_invariant,
    rel,
)

PASS = "safety"

KSTATE_FILE = "dragonboat_tpu/core/kstate.py"
KERNEL_FILE = "dragonboat_tpu/core/kernel.py"

CACHE_FILE = "dragonboat_tpu/analysis/.safety_cache.json"
#: sources whose content keys the cached model-check verdict
CACHE_SOURCES = (
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/params.py",
    "dragonboat_tpu/core/invariants.py",
    "scripts/model_check.py",
    "dragonboat_tpu/analysis/safety.py",
)

#: every file this pass reads — scripts/lint.py --changed-only scope
SCOPE = tuple(dict.fromkeys((KSTATE_FILE, KERNEL_FILE) + CACHE_SOURCES))

#: state params whose attribute reads count as ShardState field refs
_STATE_NAMES = ("s", "state", "st")
_MSG_NAMES = ("m",)

#: the quorum source: commit advances on the leader path must derive
#: from it (mirrors raft.go sortMatchValues / the kernel's jnp.sort)
_QUORUM_FN = "_sorted_match_quorum_index"


# ---------------------------------------------------------------------------
# declaration lint (RS001 / RS006)
# ---------------------------------------------------------------------------


def _literal_assign(tree: ast.Module, name: str):
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name:
            try:
                return ast.literal_eval(node.value), node
            except (ValueError, SyntaxError):
                return None, node
    return None, None


def _entry_lines(node: ast.Assign | None) -> dict[str, int]:
    out: dict[str, int] = {}
    if node is not None and isinstance(node.value, ast.Dict):
        for k in node.value.keys:
            if isinstance(k, ast.Constant):
                out[k.value] = k.lineno
    return out


def check_declarations(root: str, kstate_path: str) -> tuple[list, dict]:
    """RS001/RS006 over one kstate-shaped file; returns
    ``(findings, parsed_invariants)``."""
    findings: list[Finding] = []
    relpath = rel(root, kstate_path)
    with open(kstate_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=kstate_path)
    table, node = _literal_assign(tree, "INVARIANTS")
    if not isinstance(table, dict) or not table:
        line = node.lineno if node is not None else 1
        if node is None:
            what = "is missing"
        elif not isinstance(table, dict):
            what = "is not a pure-literal dict"
        else:
            what = "is empty"
        findings.append(Finding(
            PASS, relpath, line, "RS006",
            f"kstate INVARIANTS {what} — the runtime probe and the "
            "model checker have nothing to verify"))
        return findings, {}
    lines = _entry_lines(node)
    contracts_table, _ = _literal_assign(tree, "CONTRACTS")
    state_fields: set[str] = set()
    if isinstance(contracts_table, dict):
        try:
            parsed_c = parse_contracts(contracts_table, relpath)
            state_fields = set(parsed_c.get("ShardState", {}))
        except ValueError:
            state_fields = set(contracts_table.get("ShardState", {}))
    parsed: dict = {}
    for name, spec in table.items():
        line = lines.get(name, node.lineno)
        try:
            inv = parse_invariant(name, spec, f"{relpath}:INVARIANTS")
        except InvariantError as e:
            findings.append(Finding(PASS, relpath, line, "RS001", str(e)))
            continue
        unknown = [f for f in inv.fields if f not in state_fields]
        if state_fields and unknown:
            findings.append(Finding(
                PASS, relpath, line, "RS001",
                f"invariant {name!r} references field(s) "
                f"{sorted(unknown)} with no ShardState contract — the "
                "probe and checker would KeyError or silently skip"))
            continue
        parsed[name] = inv
    return findings, parsed


# ---------------------------------------------------------------------------
# store-obligation provenance analysis (RS002-RS004)
# ---------------------------------------------------------------------------


def _collect_defs(fn: ast.FunctionDef) -> dict[str, list[ast.AST]]:
    """name -> every expression assigned to it anywhere in the function
    (all defs are unioned during resolution — a sound over-approx)."""
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            defs.setdefault(node.targets[0].id, []).append(node.value)
        elif isinstance(node, ast.NamedExpr) \
                and isinstance(node.target, ast.Name):
            defs.setdefault(node.target.id, []).append(node.value)
    return defs


class _Prov:
    """Transitive refs of an expression through local definitions."""

    def __init__(self, defs: dict[str, list[ast.AST]]):
        self.defs = defs
        self._memo: dict[int, tuple[frozenset, frozenset]] = {}

    def refs(self, expr: ast.AST | None,
             _visiting: frozenset = frozenset()) -> tuple[set, set]:
        """``(attrs, calls)``: attrs are ``(base, field)`` pairs for
        reads like ``s.committed`` / ``m.log_index``; calls are the
        names of every function invoked in the expression's def chain."""
        attrs: set = set()
        calls: set = set()
        if expr is None:
            return attrs, calls
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in _STATE_NAMES + _MSG_NAMES:
                base = "s" if node.value.id in _STATE_NAMES else "m"
                attrs.add((base, node.attr))
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name):
                    calls.add(node.func.id)
                elif isinstance(node.func, ast.Attribute):
                    calls.add(node.func.attr)
            elif isinstance(node, ast.Name) and node.id in self.defs \
                    and node.id not in _visiting \
                    and node.id not in _STATE_NAMES + _MSG_NAMES:
                # the state/message SoA names are terminal: they are
                # rebound by every mrep, and chasing those rebindings
                # would conflate all stores in the function
                for d in self.defs[node.id]:
                    a, c = self.refs(d, _visiting | {node.id})
                    attrs |= a
                    calls |= c
        return attrs, calls


def _store_sites(fn: ast.FunctionDef):
    """Every ``mrep(s, mask, **kw)`` / ``x._replace(**kw)`` call in the
    function: ``(lineno, mask_expr_or_None, {field: value_expr})``."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if not kw:
            continue
        if isinstance(node.func, ast.Name) and node.func.id == "mrep":
            mask = node.args[1] if len(node.args) > 1 else None
            yield node.lineno, mask, kw
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "_replace":
            yield node.lineno, None, kw


def _handles_request_vote(fn: ast.FunctionDef) -> bool:
    """Whether the function dispatches on ``m.mtype == MT.REQUEST_VOTE``
    (the authoritative vote-grant handler marker)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        has_mtype = any(
            isinstance(x, ast.Attribute) and x.attr == "mtype"
            for x in sides)
        has_rv = any(
            isinstance(x, ast.Attribute) and x.attr == "REQUEST_VOTE"
            for x in sides)
        if has_mtype and has_rv:
            return True
    return False


def check_stores(root: str, kernel_path: str,
                 invariants: dict) -> list[Finding]:
    """RS002-RS004 over one kernel-shaped file."""
    findings: list[Finding] = []
    relpath = rel(root, kernel_path)
    with open(kernel_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=kernel_path)

    # obligations only exist for fields the declarations actually bind
    inv_fields = {f for inv in invariants.values() for f in inv.fields}
    want_commit = "committed" in inv_fields
    want_vote = "vote" in inv_fields
    want_last = "last" in inv_fields

    for fn in (n for n in tree.body if isinstance(n, ast.FunctionDef)):
        prov = _Prov(_collect_defs(fn))
        grants_vote = _handles_request_vote(fn)
        persisted_vote = False
        for lineno, mask, kw in _store_sites(fn):
            mask_attrs, mask_calls = prov.refs(mask)
            if want_commit and "committed" in kw:
                vattrs, vcalls = prov.refs(kw["committed"])
                monotone = ("s", "committed") in vattrs
                quorum = _QUORUM_FN in vcalls
                if quorum and ("s", "role") not in mask_attrs:
                    findings.append(Finding(
                        PASS, relpath, lineno, "RS002",
                        f"{fn.name}: quorum-derived commit advance whose "
                        "mask never checks s.role — a non-leader could "
                        "move the commit index"))
                elif not monotone and not quorum:
                    findings.append(Finding(
                        PASS, relpath, lineno, "RS002",
                        f"{fn.name}: store to ShardState.committed is "
                        "neither monotone in s.committed (the "
                        "jnp.maximum follower form) nor derived from "
                        f"{_QUORUM_FN} — commit_monotone / "
                        "leader_commit_quorum cannot be preserved"))
            if want_vote and "vote" in kw:
                vattrs, _ = prov.refs(kw["vote"])
                if ("m", "from_") in vattrs:
                    persisted_vote = True
            if want_last and "last" in kw:
                vattrs, _ = prov.refs(kw["last"])
                if ("s", "last") in vattrs:
                    continue        # append path: monotone from s.last
                if ("s", "committed") not in mask_attrs:
                    findings.append(Finding(
                        PASS, relpath, lineno, "RS004",
                        f"{fn.name}: store can LOWER ShardState.last "
                        "(value independent of s.last) but its mask "
                        "never compares against s.committed — a "
                        "replicate could truncate the committed prefix "
                        "(commit_within_log)"))
        if want_vote and grants_vote and not persisted_vote:
            findings.append(Finding(
                PASS, relpath, fn.lineno, "RS003",
                f"{fn.name}: handles RequestVote but never persists the "
                "candidate id into ShardState.vote — a granted-but-"
                "unrecorded vote breaks vote_once_per_term (two "
                "same-term candidates can each win a quorum)"))
    return findings


# ---------------------------------------------------------------------------
# cached model-check gate (RS005)
# ---------------------------------------------------------------------------


def _source_key(root: str) -> str:
    h = hashlib.sha256()
    for src in CACHE_SOURCES:
        p = os.path.join(root, src)
        if os.path.exists(p):
            with open(p, "rb") as f:
                h.update(f.read())
        h.update(b"\0")
    try:
        import jax

        h.update(jax.__version__.encode())
    except Exception:
        pass
    return h.hexdigest()


def _load_model_check(root: str):
    import importlib.util
    import sys

    path = os.path.join(root, "scripts", "model_check.py")
    spec = importlib.util.spec_from_file_location("_safety_model_check",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolve string annotations through sys.modules, so the
    # module must be registered before its body executes (py3.10)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def model_check_gate(root: str, use_cache: bool = True) -> list[Finding]:
    """RS005: the fast exhaustive scope must be clean.  Cached by source
    hash (same idiom as the hlo-budget / partition dynamic checks)."""
    relpath = KERNEL_FILE
    cache_path = os.path.join(root, CACHE_FILE)
    key = _source_key(root)
    if use_cache and os.path.exists(cache_path):
        try:
            with open(cache_path, encoding="utf-8") as f:
                cached = json.load(f)
            if cached.get("key") == key:
                return [Finding(PASS, relpath, 1, "RS005", m)
                        for m in cached.get("messages", [])]
        except (OSError, ValueError):
            pass
    mc = _load_model_check(root)
    res = mc.run_scope("fast", root=root)
    messages = [
        f"model check ({res['scope']} scope, {res['states_explored']} "
        f"states): {v['property']} violated — {v['detail']} "
        f"[trail: {' / '.join(v['trail'])}]"
        for v in res["violations"]]
    if not res["scope_complete"]:
        messages.append(
            "model check: fast scope did not complete "
            f"({res['states_explored']} states explored) — exploration "
            "budget misconfigured")
    try:
        with open(cache_path, "w", encoding="utf-8") as f:
            json.dump({"key": key, "messages": messages,
                       "states_explored": res["states_explored"],
                       "transitions": res["transitions"],
                       "frontier_exhausted": res["frontier_exhausted"]},
                      f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    return [Finding(PASS, relpath, 1, "RS005", m) for m in messages]


# ---------------------------------------------------------------------------
# pass entry point
# ---------------------------------------------------------------------------


def run(root: str, files: list[str] | None = None,
        dynamic: bool = True) -> list[Finding]:
    if files is None:
        kstate_paths = [os.path.join(root, KSTATE_FILE)]
        kernel_paths = [os.path.join(root, KERNEL_FILE)]
    else:
        kstate_paths = [p for p in files
                        if os.path.basename(p) == "kstate.py"] or files
        kernel_paths = [p for p in files
                        if os.path.basename(p) == "kernel.py"] or files
        dynamic = False

    findings: list[Finding] = []
    invariants: dict = {}
    for p in kstate_paths:
        if not os.path.exists(p):
            continue
        f, parsed = check_declarations(root, p)
        findings += f
        invariants.update(parsed)
    for p in kernel_paths:
        if not os.path.exists(p):
            continue
        findings += check_stores(root, p, invariants)
    if dynamic:
        findings += model_check_gate(root)
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))
