"""Project-native static analysis (``scripts/lint.py``).

Four passes guard the invariants the test suite cannot watch directly:

- ``tracer_safety``  — no host control flow / host syncs inside jitted scope
  (the branchless-kernel contract, core/kernel.py);
- ``hlo_budget``     — the lowered step kernel stays within the checked-in
  gather/scatter/while budget (``hlo_budget.json``; the r5 155->32
  gather prune, PERF.md, as a permanent gate);
- ``concurrency``    — ``# guarded-by: <lock>`` discipline on mutable
  attributes of classes shared across threads;
- ``determinism``    — no wall-clock, unseeded RNG, or set-iteration-order
  dependence in the core/ and rsm/ replay paths.

Pre-existing violations are either fixed or waived in ``waivers.toml``
with a one-line reason.  Each pass exposes ``run(root, files=None)``
returning ``list[common.Finding]`` so tests can point it at fixtures.
"""
