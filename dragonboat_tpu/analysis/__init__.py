"""Project-native static analysis (``scripts/lint.py``).

Five passes guard the invariants the test suite cannot watch directly:

- ``tracer_safety``  — no host control flow / host syncs inside jitted scope
  (the branchless-kernel contract, core/kernel.py);
- ``hlo_budget``     — the lowered step kernel stays within the checked-in
  gather/scatter/while budget (``hlo_budget.json``; the r5 155->32
  gather prune, PERF.md, as a permanent gate; result cached by source
  hash in ``.hlo_budget_cache.json``);
- ``concurrency``    — ``# guarded-by: <lock>`` discipline on mutable
  attributes of classes shared across threads, plus the CC003
  lock-order graph (static deadlock detection);
- ``determinism``    — no wall-clock, unseeded RNG, or set-iteration-order
  dependence in the core/ and rsm/ replay paths;
- ``contracts``      — machine-checked shape/dtype/domain/ring-mask
  contracts over the batched Raft step: an abstract interpreter over
  core/kernel.py checks the CONTRACTS declarations of core/kstate.py,
  and an eval_shape pass diffs declared vs actual structures.

Pre-existing violations are either fixed or waived in ``waivers.toml``
with a one-line reason (stale waivers are themselves lint failures).
Each pass exposes ``run(root, files=None)`` returning
``list[common.Finding]`` so tests can point it at fixtures.
"""
