"""Determinism lint for the replay paths (``core/`` and ``rsm/``).

A raft log replayed on two replicas must produce bit-identical state;
so must the kernel↔pycore differential harness.  Anything that can make
two replays diverge is banned from these modules:

- DT001  wall clock: ``time.time`` / ``monotonic`` / ``perf_counter`` /
         ``*_ns`` / ``datetime.now`` / ``utcnow`` / ``today`` — replay
         must be a pure function of the log, never of the wall;
- DT002  unseeded RNG: module-level ``random.*`` and global
         ``np.random.*`` draws (``jax.random`` is keyed and explicit,
         and the kernel's splitmix32 timeout draw is seeded state —
         both fine);
- DT003  set-iteration-order dependence: iterating a ``set`` (display,
         ``set(...)`` constructor, or a local assigned from one)
         without ``sorted()`` — CPython set order varies with insertion
         history and PYTHONHASHSEED for str keys.  Dict iteration is
         insertion-ordered and allowed.
"""

from __future__ import annotations

import ast
import glob
import os

from dragonboat_tpu.analysis.common import Finding, rel

PASS = "determinism"

DEFAULT_GLOBS = (
    "dragonboat_tpu/core/*.py",
    "dragonboat_tpu/rsm/*.py",
    # the replay-contract side of the chaos harness: plan generation,
    # fault cartridge, oracle.  runner.py is deliberately NOT listed —
    # it waits on real elections/recovery, so wall-clock use is its job;
    # the deterministic trace contract lives in these three.
    "dragonboat_tpu/chaos/faultplan.py",
    "dragonboat_tpu/chaos/crashfs.py",
    "dragonboat_tpu/chaos/oracle.py",
    # telemetry must never perturb a replay: no clocks, no randomness —
    # instruments observe caller-supplied values, the flight recorder
    # stamps records with a caller-side monotonic sequence
    "dragonboat_tpu/telemetry.py",
    "dragonboat_tpu/flight.py",
    # the lifecycle tracer follows the same contract: its microsecond
    # clock is INJECTED (tracing.monotonic_us lives outside this scope),
    # so the module itself names no wall clock
    "dragonboat_tpu/lifecycle.py",
    # the capacity rail too: the compile tracker's clock is injected,
    # flight records are stamped with call counts, never wall time
    "dragonboat_tpu/capacity.py",
    # the fabric meter: same injected-clock contract as the lifecycle
    # tracer (delivery latencies and remote-span stamps come off the
    # injected microsecond clock), and distinct-host sets are
    # insertion-ordered dicts so snapshots carry no set-order noise
    "dragonboat_tpu/fabric.py",
    # the elastic controller: decisions must be a pure function of the
    # observation sequence (digest + seeded splitmix32 tie-break) so a
    # replayed flight record reproduces every transfer — no wall clock,
    # no unseeded RNG, no set-order dependence
    "dragonboat_tpu/control.py",
)

WALL_CLOCK = {
    ("time", "time"), ("time", "time_ns"),
    ("time", "monotonic"), ("time", "monotonic_ns"),
    ("time", "perf_counter"), ("time", "perf_counter_ns"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
}

RNG_ROOTS = {"random", "np.random", "numpy.random"}


def _dotted(node: ast.AST) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_set_expr(node: ast.AST, set_names: set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
        # s.union(...), s.intersection(...) etc. on a known set
        if isinstance(f, ast.Attribute) and _is_set_expr(f.value, set_names):
            return True
    if isinstance(node, ast.Name):
        return node.id in set_names
    if isinstance(node, ast.BinOp):   # s1 | s2 on known sets
        return (_is_set_expr(node.left, set_names)
                and _is_set_expr(node.right, set_names))
    return False


class _Checker(ast.NodeVisitor):
    def __init__(self, relpath: str, findings: list[Finding]) -> None:
        self.relpath = relpath
        self.findings = findings
        self.set_names: set[str] = set()

    def _flag(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(Finding(PASS, self.relpath, node.lineno,
                                     rule, msg))

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is not None:
            parts = d.split(".")
            root, leaf = ".".join(parts[:-1]), parts[-1]
            if (root, leaf) in WALL_CLOCK or (
                    root.endswith(".datetime")
                    and leaf in ("now", "utcnow", "today")):
                self._flag(node, "DT001",
                           f"wall clock `{d}()` in a replay path — replay "
                           "must be a pure function of the log")
            elif root in RNG_ROOTS:
                self._flag(node, "DT002",
                           f"unseeded global RNG `{d}()` in a replay path "
                           "(thread a seeded generator instead)")
        self.generic_visit(node)

    def _note_assign(self, target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            if _is_set_expr(value, self.set_names):
                self.set_names.add(target.id)
            else:
                self.set_names.discard(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_assign(t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note_assign(node.target, node.value)
        self.generic_visit(node)

    def _check_iter(self, it: ast.AST) -> None:
        if _is_set_expr(it, self.set_names):
            self._flag(it, "DT003",
                       "iteration over a set in a replay path — order "
                       "varies across processes; wrap in sorted()")

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)


def run(root: str, files: list[str] | None = None) -> list[Finding]:
    if files is None:
        files = []
        for g in DEFAULT_GLOBS:
            files.extend(sorted(glob.glob(os.path.join(root, g))))
    findings: list[Finding] = []
    for p in files:
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=p)
        _Checker(rel(root, p), findings).visit(tree)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
