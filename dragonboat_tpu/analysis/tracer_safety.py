"""Tracer-safety lint: host control flow and host syncs in jitted scope.

Walks every function reachable from a ``jax.jit`` / ``jax.vmap`` /
``lax.scan`` / ``lax.fori_loop`` / ``shard_map`` / ``pallas_call`` seed
site in the kernel module set and flags, inside that traced scope:

- TS001  Python ``if`` / ``while`` (or conditional expression) whose test
         depends on a traced value — under tracing this either raises
         ``TracerBoolConversionError`` or silently bakes one branch into
         the executable;
- TS002  ``int()`` / ``float()`` / ``bool()`` / ``.item()`` / ``.tolist()``
         on a traced value (host coercion, same failure class);
- TS003  host syncs: ``np.asarray`` / ``np.array`` on a traced value,
         ``.block_until_ready()``, ``jax.device_get``;
- TS004  ``time.*`` / ``random.*`` / ``datetime.*`` / ``np.random.*`` —
         host-side effects that trace once at compile time and then
         freeze (a bench or kernel that "randomizes" per step this way
         measures one constant forever).

Traced-ness is a forward single-pass taint over each function body:
parameters are tainted unless they are jit-static for that function
(``static_argnums`` on its own decorator, or a name in
``STATIC_PARAM_NAMES`` — the repo's conventional static-argument
spellings, see that constant), and any ``jnp.*`` / ``jax.*`` result is
tainted.  Shape metadata (``.shape`` / ``.ndim`` / ``.dtype`` /
``.size`` / ``len()`` / ``isinstance()`` / ``x is None``) sanitizes, so
the kernel's static specialization branches (``if kp.onehot_reads:``,
``if x is None:``) stay clean by construction, not by waiver.

The pass is intra-module-set: calls are resolved through plain names,
``from m import f`` aliases, and ``mod.f`` attributes against the
scanned file set; anything it cannot resolve is assumed host-side and
not descended into (its *result* is still tainted when its arguments
are).
"""

from __future__ import annotations

import ast
import os

from dragonboat_tpu.analysis.common import Finding, rel

PASS = "tracer-safety"

# Modules whose jit/vmap call sites seed the traced-scope walk, plus the
# helper modules their kernels call into.
DEFAULT_MODULES = (
    "dragonboat_tpu/core/kernel.py",
    "dragonboat_tpu/core/router.py",
    "dragonboat_tpu/core/kstate.py",
    "dragonboat_tpu/core/params.py",
    "dragonboat_tpu/rsm/device_kv.py",
    "dragonboat_tpu/rsm/device_kv_pallas.py",
    "dragonboat_tpu/parallel/ici.py",
    "dragonboat_tpu/bench_loop.py",
)

# Conventional static-argument names in this repo: every jit site passes
# these via static_argnums, and the helpers thread them under the same
# spellings.  A name listed here is never treated as traced.
STATIC_PARAM_NAMES = frozenset({
    "self",          # DeviceKV methods: frozen dataclass via static_argnums=0
    "kp", "kv", "cluster", "family", "replicas", "iters",
    "write_width", "do_reads", "R", "n_local", "axis",
    "T", "D", "AB", "hash_keys", "interpret", "unroll",
})

# Attribute reads that yield static metadata, never a tracer.
META_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "at",
                        "aval", "weak_type"})

# Builtins whose result is host/static regardless of argument taint.
CLEAN_FUNCS = frozenset({"len", "isinstance", "type", "hasattr", "getattr",
                         "range", "zip", "enumerate", "sorted", "min", "max",
                         "tuple", "list", "dict", "set", "repr", "str",
                         "issubclass", "callable", "id"})

COERCE_FUNCS = frozenset({"int", "float", "bool", "complex"})
COERCE_METHODS = frozenset({"item", "tolist"})
SYNC_METHODS = frozenset({"block_until_ready", "copy_to_host_async"})
HOST_EFFECT_MODULES = frozenset({"time", "random", "datetime"})

# Call sites whose function-valued arguments enter traced scope.
TRACING_CALLS = frozenset({
    "jit", "vmap", "pmap", "scan", "fori_loop", "while_loop", "cond",
    "switch", "shard_map", "pallas_call", "checkpoint", "remat", "custom_vjp",
    "associative_scan", "map", "grad", "value_and_grad",
})
# ...except: plain builtin map() is not a tracing site; only lax.map is.
BARE_NAME_TRACING = TRACING_CALLS - {"map", "jit", "grad"}


def _callee_names(call: ast.Call) -> list[str]:
    """Function names referenced by a call argument (unwraps partial)."""
    out = []
    for a in list(call.args) + [k.value for k in call.keywords]:
        out.extend(_func_refs(a))
    return out


def _func_refs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    if isinstance(node, ast.Call):
        # functools.partial(f, ...) / jax.vmap(f) nesting
        return [n for a in [node.func] + list(node.args)
                for n in _func_refs(a)]
    if isinstance(node, ast.Lambda):
        return []          # analyzed in place as part of the enclosing scope
    return []


def _call_basename(func: ast.AST) -> str | None:
    """`jax.lax.scan` -> "scan", `vmap` -> "vmap"."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


class _Module:
    def __init__(self, path: str, tree: ast.Module) -> None:
        self.path = path
        self.tree = tree
        self.funcs: dict[str, ast.FunctionDef] = {}
        self.imports: dict[str, str] = {}   # local alias -> imported name
        self.aliases: dict[str, set[str]] = {}  # container -> funcs inside
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.setdefault(node.name, node)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.imports[a.asname or a.name] = a.name
        # module-level dispatch tables (e.g. _FAMILY_HANDLERS): a traced
        # function referencing the container calls everything inside it
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                refs = {n.id for n in ast.walk(node.value)
                        if isinstance(n, ast.Name) and n.id in self.funcs}
                if refs:
                    self.aliases[node.targets[0].id] = refs


def _static_argnum_names(fn: ast.FunctionDef) -> set[str]:
    """Parameter names pinned static by the function's own jit decorator."""
    names: set[str] = set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            try:
                val = ast.literal_eval(kw.value)
            except ValueError:
                continue
            items = val if isinstance(val, (tuple, list)) else (val,)
            for it in items:
                if isinstance(it, int) and it < len(params):
                    names.add(params[it])
                elif isinstance(it, str):
                    names.add(it)
    return names


def _is_jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        for name in _func_refs(dec):
            if name in ("jit", "vmap", "pmap"):
                return True
    return False


class _FunctionLinter(ast.NodeVisitor):
    """Single forward taint pass over one traced top-level function."""

    def __init__(self, mod: _Module, fn: ast.FunctionDef,
                 findings: list[Finding], relpath: str) -> None:
        self.mod = mod
        self.findings = findings
        self.relpath = relpath
        self.tainted: set[str] = set()
        self._flagged_lines: set[tuple[int, str]] = set()
        self._bind_params(fn)

    # -- parameter and name binding -------------------------------------
    def _bind_params(self, fn: ast.FunctionDef | ast.Lambda) -> None:
        static = STATIC_PARAM_NAMES | (
            _static_argnum_names(fn) if isinstance(fn, ast.FunctionDef)
            else set())
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a.arg not in static:
                self.tainted.add(a.arg)

    def _bind_target(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind_target(el, tainted)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, tainted)
        # attribute/subscript stores don't create local names

    # -- reporting ------------------------------------------------------
    def _flag(self, node: ast.AST, rule: str, msg: str) -> None:
        key = (node.lineno, rule)
        if key in self._flagged_lines:
            return
        self._flagged_lines.add(key)
        self.findings.append(Finding(PASS, self.relpath, node.lineno,
                                     rule, msg))

    # -- taint evaluation (with side-effect flagging of bad calls) ------
    def _taint(self, node: ast.AST | None) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in META_ATTRS:
                return False
            return self._taint(node.value)
        if isinstance(node, ast.Call):
            return self._taint_call(node)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                # identity tests are host decisions (x is None)
                for sub in [node.left] + node.comparators:
                    self._taint(sub)   # still surface bad calls inside
                return False
            return any(self._taint(x)
                       for x in [node.left] + node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self._taint(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self._taint(node.left) | self._taint(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._taint(node.operand)
        if isinstance(node, ast.Subscript):
            return self._taint(node.value) | self._taint(node.slice)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._taint(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self._taint(x)
                       for x in list(node.keys) + list(node.values) if x)
        if isinstance(node, ast.IfExp):
            if self._taint(node.test):
                self._flag(node, "TS001",
                           "conditional expression on a traced value")
            return self._taint(node.body) | self._taint(node.orelse)
        if isinstance(node, ast.Starred):
            return self._taint(node.value)
        if isinstance(node, ast.Lambda):
            # analyzed when called at a tracing site; the object is clean
            return False
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            t = False
            for gen in node.generators:
                it = self._taint(gen.iter)
                self._bind_target(gen.target, it)
                t |= it
            if isinstance(node, ast.DictComp):
                return t | self._taint(node.key) | self._taint(node.value)
            return t | self._taint(node.elt)
        if isinstance(node, ast.JoinedStr):
            return any(self._taint(v) for v in node.values)
        if isinstance(node, ast.FormattedValue):
            return self._taint(node.value)
        if isinstance(node, ast.Slice):
            return (self._taint(node.lower) | self._taint(node.upper)
                    | self._taint(node.step))
        if isinstance(node, ast.NamedExpr):
            t = self._taint(node.value)
            self._bind_target(node.target, t)
            return t
        return False   # unknown node kinds: assume host-static

    def _root_module(self, node: ast.AST) -> str | None:
        """`time.monotonic` -> "time"; `np.random.rand` -> "np.random"."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            parts.reverse()
            return ".".join(parts[:-1]) if len(parts) > 1 else None
        return None

    def _taint_call(self, node: ast.Call) -> bool:
        func = node.func
        args_tainted = any(self._taint(a) for a in node.args) or any(
            self._taint(k.value) for k in node.keywords)

        if isinstance(func, ast.Name):
            if func.id in CLEAN_FUNCS:
                return False
            if func.id in COERCE_FUNCS and args_tainted:
                self._flag(node, "TS002",
                           f"{func.id}() on a traced value forces a host "
                           "sync / concretization inside jitted scope")
                return False
        if isinstance(func, ast.Attribute):
            root = self._root_module(func)
            if root in HOST_EFFECT_MODULES or root in (
                    "np.random", "numpy.random"):
                self._flag(node, "TS004",
                           f"{root}.{func.attr}() inside traced scope "
                           "executes once at trace time and freezes")
                return False
            if func.attr in COERCE_METHODS and self._taint(func.value):
                self._flag(node, "TS002",
                           f".{func.attr}() on a traced value")
                return False
            if func.attr in SYNC_METHODS:
                self._flag(node, "TS003",
                           f".{func.attr}() host sync inside traced scope")
                return False
            if func.attr == "device_get":
                self._flag(node, "TS003",
                           "jax.device_get() inside traced scope")
                return False
            if root in ("np", "numpy") and func.attr in (
                    "asarray", "array") and args_tainted:
                self._flag(node, "TS003",
                           f"{root}.{func.attr}() on a traced value pulls "
                           "the buffer to host")
                return False
            if root is not None and root.split(".")[0] in (
                    "jnp", "jax", "lax", "plax", "pl"):
                return True        # jax-family result: a tracer
            if self._taint(func.value):
                return True        # method on a tracer yields a tracer
        # helper call: traced result iff any traced argument flowed in
        return args_tainted

    # -- statements -----------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        t = self._taint(node.value)
        for tgt in node.targets:
            self._bind_target(tgt, t)
            self._taint(tgt)       # flag bad calls in subscript targets

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._bind_target(node.target, self._taint(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        t = self._taint(node.value) or self._taint(node.target)
        self._bind_target(node.target, t)

    def _isinstance_narrowed(self, test: ast.AST) -> set[str]:
        """Names proven host-typed by an ``isinstance(x, ...)`` test."""
        if (isinstance(test, ast.Call) and isinstance(test.func, ast.Name)
                and test.func.id == "isinstance" and test.args
                and isinstance(test.args[0], ast.Name)):
            return {test.args[0].id}
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            out: set[str] = set()
            for v in test.values:
                out |= self._isinstance_narrowed(v)
            return out
        return set()

    def visit_If(self, node: ast.If) -> None:
        if self._taint(node.test):
            self._flag(node, "TS001",
                       "Python `if` on a traced value inside jitted scope "
                       "(use jnp.where / lax.cond)")
        narrowed = self._isinstance_narrowed(node.test) & self.tainted
        self.tainted -= narrowed
        for st in node.body:
            self.visit(st)
        self.tainted |= narrowed
        for st in node.orelse:
            self.visit(st)

    def visit_While(self, node: ast.While) -> None:
        if self._taint(node.test):
            self._flag(node, "TS001",
                       "Python `while` on a traced value inside jitted "
                       "scope (use lax.while_loop)")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        it = self._taint(node.iter)
        # dict-structure iteration is static control flow (the key set is
        # a trace-time constant) even when the VALUES are tracers
        if (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Attribute)
                and node.iter.func.attr in ("items", "keys", "values")):
            self._bind_target(node.target, it)
            self.generic_visit(node)
            return
        if it:
            self._flag(node, "TS001",
                       "Python `for` over a traced value inside jitted "
                       "scope (use lax.scan / fori_loop)")
        self._bind_target(node.target, it)
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        self._taint(node.value)

    def visit_Return(self, node: ast.Return) -> None:
        self._taint(node.value)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._taint(node.test)     # surface bad calls; asserts themselves ok

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs (scan/fori bodies, routers' closures) are traced
        # with the parent; their params are fresh tracers
        self._bind_params(node)
        for st in node.body:
            self.visit(st)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._bind_params(node)
        self._taint(node.body)

    def run(self, fn: ast.FunctionDef) -> None:
        for st in fn.body:
            self.visit(st)
        # lambdas appearing in expression statements are visited via
        # _taint -> visit? no: evaluate them explicitly
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Lambda):
                self.visit_Lambda(sub)


def _seed_and_calls(mod: _Module) -> tuple[set[str], dict[str, set[str]]]:
    """(traced seed function names, per-function called-name sets)."""
    seeds: set[str] = set()
    calls: dict[str, set[str]] = {name: set() for name in mod.funcs}

    for name, fn in mod.funcs.items():
        if _is_jit_decorated(fn):
            seeds.add(name)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in mod.aliases:
                calls[name].update(mod.aliases[node.id])
            if not isinstance(node, ast.Call):
                continue
            base = _call_basename(node.func)
            refs = _func_refs(node.func) + _callee_names(node)
            calls[name].update(
                n for n in refs if n in mod.funcs or n in mod.imports)
            if base in TRACING_CALLS and (
                    isinstance(node.func, ast.Attribute)
                    or base in BARE_NAME_TRACING):
                for ref in _callee_names(node):
                    if ref not in TRACING_CALLS and ref != "partial":
                        seeds.add(ref)
    return seeds, calls


def run(root: str, files: list[str] | None = None) -> list[Finding]:
    paths = files if files is not None else [
        os.path.join(root, m) for m in DEFAULT_MODULES]
    mods: list[_Module] = []
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            mods.append(_Module(p, ast.parse(f.read(), filename=p)))

    # global name -> (module, fn): resolve `from m import f` across the set
    global_funcs: dict[str, tuple[_Module, ast.FunctionDef]] = {}
    for m in mods:
        for name, fn in m.funcs.items():
            global_funcs.setdefault(name, (m, fn))

    # seed + propagate reachability over the whole set
    traced: set[str] = set()
    all_calls: dict[str, set[str]] = {}
    for m in mods:
        seeds, calls = _seed_and_calls(m)
        traced |= seeds
        for name, callees in calls.items():
            all_calls.setdefault(name, set()).update(
                m.imports.get(c, c) for c in callees)

    frontier = list(traced)
    while frontier:
        name = frontier.pop()
        for callee in all_calls.get(name, ()):
            if callee in global_funcs and callee not in traced:
                traced.add(callee)
                frontier.append(callee)

    findings: list[Finding] = []
    for name in sorted(traced):
        if name not in global_funcs:
            continue
        mod, fn = global_funcs[name]
        linter = _FunctionLinter(mod, fn, findings, rel(root, mod.path))
        linter.run(fn)
    # nested defs are analyzed both standalone and within their parent
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))
