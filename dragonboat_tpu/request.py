"""Request futures + pending-operation books.

Parity with the reference's ``request.go``: every async op returns a
RequestState whose completion fires when the op commits/applies
(RequestState :294, pendingProposal :524, pendingReadIndex :535,
pendingConfigChange :549, pendingSnapshot :557, pendingLeaderTransfer :564),
with tick-driven timeout GC (logicalClock :236).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from enum import IntEnum

from dragonboat_tpu import lifecycle
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.statemachine import Result


class RequestResultCode(IntEnum):
    """Parity request.go:116 (RequestResult codes)."""

    TIMEOUT = 0
    COMPLETED = 1
    TERMINATED = 2
    REJECTED = 3
    DROPPED = 4
    ABORTED = 5
    COMMITTED = 6


class RequestError(Exception):
    pass


class RequestTimeoutError(RequestError):
    pass


class RequestRejectedError(RequestError):
    pass


class RequestDroppedError(RequestError):
    """No leader / busy — retry later (ErrShardNotReady analog)."""


class RequestTerminatedError(RequestError):
    pass


@dataclass
class RequestResult:
    code: RequestResultCode = RequestResultCode.TIMEOUT
    result: Result = field(default_factory=Result)
    snapshot_index: int = 0

    def completed(self) -> bool:
        return self.code == RequestResultCode.COMPLETED


class RequestState:
    """A completion future (request.go:294)."""

    def __init__(self, key: int = 0, deadline_tick: int = 0) -> None:
        self.key = key
        self.deadline_tick = deadline_tick
        self._event = threading.Event()
        self.result = RequestResult()
        self.committed_event = threading.Event()

    def notify(self, result: RequestResult) -> None:
        self.result = result
        self._event.set()

    def notify_committed(self) -> None:
        self.committed_event.set()

    def wait(self, timeout_s: float | None = None) -> RequestResult:
        if not self._event.wait(timeout_s):
            return RequestResult(code=RequestResultCode.TIMEOUT)
        return self.result

    def get(self, timeout_s: float | None = None) -> Result:
        """Blocking result with error mapping (SyncPropose semantics)."""
        r = self.wait(timeout_s)
        if r.code == RequestResultCode.COMPLETED:
            return r.result
        if r.code == RequestResultCode.TIMEOUT:
            raise RequestTimeoutError("request timed out")
        if r.code == RequestResultCode.REJECTED:
            raise RequestRejectedError("request rejected")
        if r.code == RequestResultCode.DROPPED:
            raise RequestDroppedError("request dropped, shard not ready")
        if r.code == RequestResultCode.TERMINATED:
            raise RequestTerminatedError("shard terminated")
        raise RequestError(f"request failed: {r.code}")


class LogicalClock:
    """One absolute tick counter shared by every request book of a host
    (request.go:236 logicalClock).  The host ticker advances it ONCE per
    tick round; books stamp deadlines against it and compare absolutely
    — the per-book per-lane ``advance()`` walk this replaces was the
    dominant cost of the 100k-lane election pump (~25 s/tick-round of
    pure Python increments, PERF.md)."""

    __slots__ = ("tick",)

    def __init__(self) -> None:
        self.tick = 0

    def advance(self) -> None:
        self.tick += 1


class _ClockedBook:
    """Timeout machinery against a (possibly shared) LogicalClock."""

    def __init__(self, clock: LogicalClock | None = None) -> None:
        self.mu = threading.Lock()
        self.clock = clock if clock is not None else LogicalClock()

    @property
    def tick(self) -> int:
        return self.clock.tick

    def advance(self) -> None:
        """Standalone-book compatibility (tests construct books without
        a host); hosts advance the SHARED clock once per round instead."""
        self.clock.advance()


class PendingProposal(_ClockedBook):
    """Proposal completion book keyed by entry Key (request.go:524/1016).

    Sharded by ``key % shards`` the way the reference splits its book
    into keyed shards (request.go:524 pendingProposal holds N
    proposalShards) so concurrent client threads completing/registering
    different keys never serialize on one lock — the engine's apply
    path touches a different shard than the ingress path almost always.
    The logical clock stays book-wide (ticks are engine-driven).

    Lifecycle tracing: entry keys come off the CLASS-level ``_seq``, so
    they are process-unique — the 1-in-N span sampling in lifecycle.py
    keys off them directly.  Every verb that removes a key from this
    book ends its span: ``applied`` finishes it (the ack), while
    ``dropped``/``gc``/``terminate_all`` scrub it — including the
    engine's in-flight-removal paths, which all funnel through
    ``dropped`` — so the span registry can never outlive the book."""

    _seq = itertools.count(1)

    def __init__(self, shards: int = 8,
                 clock: LogicalClock | None = None,
                 shard_id: int = 0) -> None:
        super().__init__(clock)
        self._shards: list[dict[int, RequestState]] = [   # guarded-by: _locks
            {} for _ in range(shards)]
        self._locks = [threading.Lock() for _ in range(shards)]
        self._n = shards                                  # guarded-by: <init-only>
        # raft shard id this book serves (Chrome-trace pid grouping)
        self.shard_id = shard_id                          # guarded-by: <init-only>

    @property
    def pending(self) -> dict[int, RequestState]:
        """Merged read-only view (tests/diagnostics)."""
        out: dict[int, RequestState] = {}
        for d in self._shards:
            out.update(d)
        return out

    def propose(self, session, cmd: bytes, timeout_ticks: int
                ) -> tuple[RequestState, pb.Entry]:
        key = next(self._seq)
        entry = pb.Entry(
            key=key,
            client_id=session.client_id,
            series_id=session.series_id,
            responded_to=session.responded_to,
            cmd=cmd,
        )
        rs = RequestState(key=key, deadline_tick=self.tick + timeout_ticks)
        i = key % self._n
        with self._locks[i]:
            self._shards[i][key] = rs
        lifecycle.TRACER.begin(key, self.shard_id)
        return rs, entry

    def applied(self, key: int, client_id: int, series_id: int,
                result: Result, rejected: bool) -> None:
        i = key % self._n
        with self._locks[i]:
            rs = self._shards[i].pop(key, None)
        if rs is not None:
            code = (RequestResultCode.REJECTED if rejected
                    else RequestResultCode.COMPLETED)
            rs.notify(RequestResult(code=code, result=result))
            lifecycle.TRACER.finish(key)

    def committed(self, key: int) -> None:
        i = key % self._n
        with self._locks[i]:
            rs = self._shards[i].get(key)
        if rs is not None:
            rs.notify_committed()

    def dropped(self, key: int) -> None:
        i = key % self._n
        with self._locks[i]:
            rs = self._shards[i].pop(key, None)
        if rs is not None:
            rs.notify(RequestResult(code=RequestResultCode.DROPPED))
            lifecycle.TRACER.scrub(key)

    def gc(self) -> None:
        # unlocked emptiness fast path: the amortized host sweep calls
        # gc on EVERY lane's books; an entry racing in is caught by the
        # next sweep (timeouts are tick-granular anyway)
        if not any(self._shards):
            return
        for i in range(self._n):
            with self._locks[i]:
                d = self._shards[i]
                expired = [k for k, rs in d.items()
                           if rs.deadline_tick <= self.tick]
                fired = [d.pop(k) for k in expired]
            for k, rs in zip(expired, fired):
                rs.notify(RequestResult(code=RequestResultCode.TIMEOUT))
                lifecycle.TRACER.scrub(k)

    def terminate_all(self) -> None:
        for i in range(self._n):
            with self._locks[i]:
                fired = list(self._shards[i].items())
                self._shards[i].clear()
            for k, rs in fired:
                rs.notify(RequestResult(code=RequestResultCode.TERMINATED))
                lifecycle.TRACER.scrub(k)


class PendingReadIndex(_ClockedBook):
    """ReadIndex completion book (request.go:535): batches reads under a
    SystemCtx, fires when appliedIndex passes the read index (:930).

    Lifecycle tracing (ROADMAP item 3's attribution prerequisite): read
    keys come off ``PendingProposal._seq`` — the SAME process-unique
    counter as entry keys, so sampling stays 1-in-N over all traced
    operations and a read key can never collide with a proposal span.
    ``read`` opens the span (``read_propose``), ``add_ready`` stamps the
    confirmed quorum round (``read_quorum``), ``applied`` finishes it at
    serve time (``read_serve``); every removal verb scrubs."""

    _ctx = itertools.count(1)

    def __init__(self, clock: LogicalClock | None = None,
                 shard_id: int = 0) -> None:
        super().__init__(clock)
        self.pending: dict[int, list[RequestState]] = {}   # guarded-by: mu — ctx_low -> readers
        self.batching: list[RequestState] = []             # guarded-by: mu
        self.ready: dict[int, int] = {}                    # guarded-by: mu — ctx_low -> index
        self.waiting: list[tuple[int, RequestState]] = []  # guarded-by: mu — (index, rs)
        # raft shard id this book serves (Chrome-trace pid grouping)
        self.shard_id = shard_id                           # guarded-by: <init-only>

    def read(self, timeout_ticks: int) -> RequestState:
        key = next(PendingProposal._seq)
        rs = RequestState(key=key, deadline_tick=self.tick + timeout_ticks)
        with self.mu:
            self.batching.append(rs)
        lifecycle.TRACER.begin_read(key, self.shard_id)
        return rs

    def peep(self) -> pb.SystemCtx | None:
        """Take the current batch under a fresh ctx (nextCtx/peepNextCtx)."""
        with self.mu:
            if not self.batching:
                return None
            ctx = pb.SystemCtx(low=next(self._ctx), high=1)
            self.pending[ctx.low] = self.batching
            self.batching = []
            return ctx

    def add_ready(self, ctx: pb.SystemCtx, index: int) -> None:
        with self.mu:
            readers = self.pending.pop(ctx.low, None)
            if readers is None:
                return
            self.waiting.extend((index, rs) for rs in readers)
        for rs in readers:
            lifecycle.TRACER.stamp(rs.key, lifecycle.STAGE_READ_QUORUM)

    def applied(self, applied_index: int) -> None:
        """Fire every waiting read whose index has been applied."""
        with self.mu:
            still = []
            fire = []
            for index, rs in self.waiting:
                if applied_index >= index:
                    fire.append(rs)
                else:
                    still.append((index, rs))
            self.waiting = still
        for rs in fire:
            rs.notify(RequestResult(code=RequestResultCode.COMPLETED))
            lifecycle.TRACER.finish(rs.key)

    def dropped(self, ctx: pb.SystemCtx) -> None:
        with self.mu:
            readers = self.pending.pop(ctx.low, None)
        for rs in readers or ():
            rs.notify(RequestResult(code=RequestResultCode.DROPPED))
            lifecycle.TRACER.scrub(rs.key)

    def gc(self) -> None:
        # unlocked fast path (racy-but-benign: a concurrent add is
        # caught by the next sweep)
        if not (self.batching or self.waiting or self.pending):
            return
        with self.mu:
            def expire(lst):
                live, dead = [], []
                for item in lst:
                    rs = item[1] if isinstance(item, tuple) else item
                    (dead if rs.deadline_tick <= self.tick else live).append(item)
                return live, dead

            self.batching, dead1 = expire(self.batching)
            self.waiting, dead2 = expire(self.waiting)
            # readers parked under an in-flight ctx (peep() issued, the
            # quorum round lost to a leader change that never reported
            # the ctx back) must still time out — request.go's
            # pendingReadIndex gc scans its pending batches the same way
            dead3 = []
            for ctx_low, readers in list(self.pending.items()):
                live = [rs for rs in readers
                        if rs.deadline_tick > self.tick]
                dead3 += [rs for rs in readers
                          if rs.deadline_tick <= self.tick]
                if live:
                    self.pending[ctx_low] = live
                else:
                    del self.pending[ctx_low]
        for item in dead1 + dead2:
            rs = item[1] if isinstance(item, tuple) else item
            rs.notify(RequestResult(code=RequestResultCode.TIMEOUT))
            lifecycle.TRACER.scrub(rs.key)
        for rs in dead3:
            rs.notify(RequestResult(code=RequestResultCode.TIMEOUT))
            lifecycle.TRACER.scrub(rs.key)

    def terminate_all(self) -> None:
        with self.mu:
            all_rs = list(self.batching)
            all_rs += [rs for readers in self.pending.values() for rs in readers]
            all_rs += [rs for _, rs in self.waiting]
            self.batching, self.pending, self.waiting = [], {}, []
        for rs in all_rs:
            rs.notify(RequestResult(code=RequestResultCode.TERMINATED))
            lifecycle.TRACER.scrub(rs.key)


class PendingSingleton(_ClockedBook):
    """One-in-flight book for config change / snapshot / transfer
    (request.go:549-570)."""

    def __init__(self, clock: LogicalClock | None = None) -> None:
        super().__init__(clock)
        self.key_seq = itertools.count(1)
        self.outstanding: RequestState | None = None       # guarded-by: mu
        self.key = 0                                       # guarded-by: mu

    def request(self, timeout_ticks: int) -> tuple[RequestState, int]:
        with self.mu:
            if self.outstanding is not None:
                raise RequestError("another request is already outstanding")
            self.key = next(self.key_seq)
            rs = RequestState(key=self.key,
                              deadline_tick=self.tick + timeout_ticks)
            self.outstanding = rs
            return rs, self.key

    def done(self, key: int, code: RequestResultCode,
             result: Result = Result(), snapshot_index: int = 0) -> None:
        with self.mu:
            if self.outstanding is None or self.key != key:
                return
            rs, self.outstanding = self.outstanding, None
        rs.notify(RequestResult(code=code, result=result,
                                snapshot_index=snapshot_index))

    def gc(self) -> None:
        if self.outstanding is None:              # unlocked fast path
            return
        with self.mu:
            rs = self.outstanding
            if rs is not None and rs.deadline_tick <= self.tick:
                self.outstanding = None
            else:
                rs = None
        if rs is not None:
            rs.notify(RequestResult(code=RequestResultCode.TIMEOUT))

    def terminate_all(self) -> None:
        with self.mu:
            rs, self.outstanding = self.outstanding, None
        if rs is not None:
            rs.notify(RequestResult(code=RequestResultCode.TERMINATED))
