"""Client sessions — at-most-once proposal dedup handles.

Parity with the reference's ``client/`` package: a Session is
{client_id, series_id, responded_to} (client/session.pb.go:47-52); a NoOP
session (:79) skips dedup.  ``proposal_completed`` advances series_id
(:420) after a successful SyncPropose; ``prepare_for_*`` flags the session
record for registration/unregistration proposals.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass, field

from dragonboat_tpu import raftpb as pb

NOT_SESSION_MANAGED_CLIENT_ID = 0


@dataclass
class Session:
    shard_id: int
    client_id: int
    series_id: int = pb.SERIES_ID_FIRST_PROPOSAL
    responded_to: int = 0

    @staticmethod
    def new_session(shard_id: int) -> "Session":
        # reference uses a random uint64 client id
        return Session(shard_id=shard_id, client_id=secrets.randbits(63) | 1)

    @staticmethod
    def new_noop_session(shard_id: int) -> "Session":
        return Session(
            shard_id=shard_id,
            client_id=NOT_SESSION_MANAGED_CLIENT_ID,
            series_id=pb.NOOP_SERIES_ID,
        )

    def is_noop_session(self) -> bool:
        return self.series_id == pb.NOOP_SERIES_ID and self.client_id == 0

    def prepare_for_register(self) -> None:
        self.series_id = pb.SERIES_ID_FOR_REGISTER

    def prepare_for_unregister(self) -> None:
        self.series_id = pb.SERIES_ID_FOR_UNREGISTER

    def prepare_for_propose(self) -> None:
        self.series_id = pb.SERIES_ID_FIRST_PROPOSAL

    def proposal_completed(self) -> None:
        """Advance after a completed proposal (client/session.pb.go:420)."""
        self.responded_to = self.series_id
        self.series_id += 1

    def valid_for_proposal(self, shard_id: int) -> bool:
        if self.shard_id != shard_id:
            return False
        if self.is_noop_session():
            return True
        return (
            self.client_id != 0
            and self.series_id != pb.SERIES_ID_FOR_REGISTER
            and self.series_id != pb.SERIES_ID_FOR_UNREGISTER
        )

    def valid_for_session_op(self, shard_id: int) -> bool:
        if self.shard_id != shard_id or self.is_noop_session():
            return False
        return self.client_id != 0 and self.series_id in (
            pb.SERIES_ID_FOR_REGISTER,
            pb.SERIES_ID_FOR_UNREGISTER,
        )
