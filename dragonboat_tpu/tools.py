"""tools — offline cluster repair utilities.

Parity with the reference's ``tools`` package, chiefly ImportSnapshot
(tools/import.go:134): when a shard has permanently lost its quorum, an
exported snapshot is imported into selected node-host data dirs with a
REWRITTEN membership, so the survivors restart as a fresh quorum holding
the old state machine data.

Exported snapshots (``sync_request_snapshot(export_path=...)``) are the
SM image file plus a JSON metadata sidecar (``<path>.meta.json``) holding
index/term/membership/shard — the analog of the reference's exported
snapshot dir with its flag file (tools/import.go getSnapshotRecord).
"""

from __future__ import annotations

import json
import os

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import NodeHostConfig
from dragonboat_tpu.logdb.tan import TanLogDB
from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.server.env import Env
from dragonboat_tpu.vfs import copy_file

_LOG = get_logger("tools")

META_SUFFIX = ".meta.json"


def write_export_metadata(path: str, ss: pb.Snapshot, fs=None) -> None:
    """Sidecar written next to an exported snapshot image."""
    from dragonboat_tpu.vfs import default_fs

    fs = fs if fs is not None else default_fs()
    meta = {
        "shard_id": ss.shard_id,
        "index": ss.index,
        "term": ss.term,
        "type": int(ss.type),
        "membership": {
            "config_change_id": ss.membership.config_change_id,
            "addresses": {str(k): v
                          for k, v in ss.membership.addresses.items()},
            "non_votings": {str(k): v
                            for k, v in ss.membership.non_votings.items()},
            "witnesses": {str(k): v
                          for k, v in ss.membership.witnesses.items()},
        },
        # external snapshot files (rsm/files.go): recorded by basename —
        # they travel NEXT TO the exported image
        "files": [
            {
                "file_id": f.file_id,
                "basename": os.path.basename(f.filepath),
                "file_size": f.file_size,
                "metadata_hex": f.metadata.hex(),
            }
            for f in ss.files
        ],
    }
    tmp = path + META_SUFFIX + ".tmp"
    with fs.open(tmp, "w") as f:
        json.dump(meta, f)
        fs.fsync(f)
    fs.replace(tmp, path + META_SUFFIX)


def read_export_metadata(path: str, fs=None) -> dict:
    from dragonboat_tpu.vfs import default_fs

    fs = fs if fs is not None else default_fs()
    with fs.open(path + META_SUFFIX, "r") as f:
        return json.loads(f.read())


def import_snapshot(nhconfig: NodeHostConfig, src_path: str,
                    members: dict[int, str], replica_id: int) -> None:
    """ImportSnapshot (tools/import.go:134): place an exported snapshot
    into ``replica_id``'s data dir with membership REWRITTEN to
    ``members``, so the next ``start_replica`` restarts from it.

    Must run while the target NodeHost is DOWN (the env lock enforces
    this).  Every member of ``members`` must run the same import against
    its own data dir before any of them restarts."""
    if replica_id not in members:
        raise ValueError(f"replica {replica_id} not in the new membership")
    from dragonboat_tpu.vfs import default_fs

    fs = (nhconfig.expert.fs if nhconfig.expert.fs is not None
          else default_fs())
    meta = read_export_metadata(src_path, fs=fs)
    membership = pb.Membership(
        config_change_id=meta["index"],
        addresses=dict(members),
    )
    env = Env(nhconfig.node_host_dir, nhconfig.raft_address,
              nhconfig.deployment_id, wal_dir=nhconfig.wal_dir, fs=fs)
    env.lock()
    try:
        env.check_node_host_dir("sharded-tan", compatible=("tan",))
        shard_id = int(meta["shard_id"])
        # place the image in the replica's snapshot dir
        dst_dir = env.snapshot_dir(shard_id, replica_id)
        index = int(meta["index"])
        dst = os.path.join(
            dst_dir,
            f"snapshot-{shard_id:016X}-{replica_id:016X}-{index:016X}"
            ".gbsnap")
        copy_file(fs, src_path, dst)
        # external snapshot files travel next to the exported image and
        # land next to the imported one
        files = []
        src_dir = os.path.dirname(src_path) or "."
        for fm in meta.get("files", ()):
            src_f = os.path.join(src_dir, fm["basename"])
            dst_f = f"{dst}.xf{fm['file_id']}"
            copy_file(fs, src_f, dst_f)
            files.append(pb.SnapshotFile(
                file_id=int(fm["file_id"]), filepath=dst_f,
                metadata=bytes.fromhex(fm.get("metadata_hex", "")),
                file_size=int(fm["file_size"])))
        ss = pb.Snapshot(
            filepath=dst,
            file_size=fs.getsize(dst),
            index=index,
            term=int(meta["term"]),
            membership=membership,
            shard_id=shard_id,
            type=pb.StateMachineType(meta.get("type", 0)),
            imported=True,
            files=tuple(files),
        )
        # rebuild the replica's log-db state around the imported snapshot:
        # drop old state, stamp the snapshot + bootstrap (import.go main
        # flow: ssEnv.FinalizeSnapshot + logdb writes)
        # open the dir's own engine: the geometry the owning NodeHost
        # pinned (TANSHARDS marker), or the default sharded layout for a
        # fresh/legacy dir — a flat TanLogDB here would strand the
        # R_REMOVE + import records outside the partitions
        from dragonboat_tpu.logdb.sharded import ShardedLogDB

        stored = ShardedLogDB.stored_shard_count(env.logdb_dir, fs)
        db = ShardedLogDB(
            env.logdb_dir,
            num_shards=(stored if stored is not None
                        else nhconfig.expert.logdb.shards),
            fs=fs)
        try:
            db.import_snapshot(ss, replica_id)
        finally:
            db.close()
        _LOG.info("imported snapshot idx=%d for shard %d replica %d into %s",
                  index, shard_id, replica_id, env.root)
    finally:
        env.close()
